"""Fused RMSNorm Pallas kernel (fwd + custom VJP).

One HBM round-trip per row instead of the three XLA emits when the norm
fails to fuse into its neighbours (long rows, small batch). The backward
dx is also a single kernel; dw is a plain reduction XLA handles well.

No reference-counterpart: hellofinch/ray ships no kernels (SURVEY.md §2.4);
this is TPU-native green-field.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ray_tpu.ops.pallas._util import cdiv, interpret_mode

_BLOCK_ROWS = 256


def _fwd_kernel(x_ref, w_ref, o_ref, rstd_ref, *, eps: float):
    x = x_ref[:].astype(jnp.float32)
    var = jnp.mean(x * x, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    rstd_ref[:] = rstd
    o_ref[:] = (x * rstd * w_ref[:].astype(jnp.float32)).astype(o_ref.dtype)


def _bwd_dx_kernel(x_ref, w_ref, rstd_ref, g_ref, dx_ref):
    x = x_ref[:].astype(jnp.float32)
    w = w_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32)
    rstd = rstd_ref[:]
    d = x.shape[-1]
    wg = w * g
    # dL/dx = rstd * (w*g - x * rstd^2 * mean(w*g*x))
    proj = jnp.sum(wg * x, axis=-1, keepdims=True) / d
    dx_ref[:] = (rstd * (wg - x * rstd * rstd * proj)).astype(dx_ref.dtype)


def _run_fwd(x2d, w, eps):
    rows, d = x2d.shape
    block = min(_BLOCK_ROWS, rows)
    grid = (cdiv(rows, block),)
    out, rstd = pl.pallas_call(
        functools.partial(_fwd_kernel, eps=eps),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block, d), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((d,), lambda i: (0,), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((block, d), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((block, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rows, d), x2d.dtype),
            jax.ShapeDtypeStruct((rows, 1), jnp.float32),
        ],
        interpret=interpret_mode(),
    )(x2d, w)
    return out, rstd


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def rms_norm_pallas(x: jax.Array, weight: jax.Array, eps: float = 1e-5) -> jax.Array:
    """Fused RMSNorm over the last axis. Any leading shape."""
    shape = x.shape
    x2d = x.reshape(-1, shape[-1])
    out, _ = _run_fwd(x2d, weight, eps)
    return out.reshape(shape)


def _vjp_fwd(x, weight, eps):
    shape = x.shape
    x2d = x.reshape(-1, shape[-1])
    out, rstd = _run_fwd(x2d, weight, eps)
    return out.reshape(shape), (x2d, weight, rstd, shape)


def _vjp_bwd(eps, res, g):
    x2d, weight, rstd, shape = res
    g2d = g.reshape(-1, shape[-1])
    rows, d = x2d.shape
    block = min(_BLOCK_ROWS, rows)
    grid = (cdiv(rows, block),)
    dx = pl.pallas_call(
        _bwd_dx_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((block, d), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((d,), lambda i: (0,), memory_space=pltpu.VMEM),
            pl.BlockSpec((block, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((block, d), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((block, d), lambda i: (i, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((rows, d), x2d.dtype),
        interpret=interpret_mode(),
    )(x2d, weight, rstd, g2d)
    # dw: reduction over all rows — XLA's reduce is optimal here.
    xf = x2d.astype(jnp.float32)
    dw = jnp.sum(g2d.astype(jnp.float32) * xf * rstd, axis=0).astype(weight.dtype)
    return dx.reshape(shape), dw


rms_norm_pallas.defvjp(_vjp_fwd, _vjp_bwd)
