"""Fused AdamW + global-norm-clip update kernel.

The optimizer phase is HBM-bound: optax's chain (clip scale -> mu/nu
update -> bias correction -> weight decay -> apply) reads and writes the
full fp32 moment state plus params and grads. One Pallas pass per leaf does
the whole update — read p (bf16), g, mu, nu (f32); write p', mu', nu' —
the roofline minimum of 22 bytes/param. The global grad norm is computed
outside (one fused XLA reduction) and enters as a scalar.

Matches optax.chain(clip_by_global_norm, adamw) semantics (bias-corrected
moments, decoupled weight decay, mu_dtype=f32); equality is unit-tested
against optax. Leaves whose size does not tile by (8, 128) fall back to
the jnp expression of the same math.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, NamedTuple, Union

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ray_tpu.ops.pallas._util import interpret_mode

_LANES = 128
_ROWS = 512  # rows per grid block: (512, 128) f32 blocks, ~0.75 MB x 7 bufs
# How many of the largest leaves take the Pallas path (the rest use the jnp
# fallback). The axon tunnel's AOT helper has crashed on full-step programs
# with many optimizer custom calls; this caps the count while covering the
# bulk of the bytes (the 8 stacked layer leaves are ~90% of a 1B model).
PALLAS_LEAVES = 16


def _adamw_kernel(scal_ref, p_ref, g_ref, mu_ref, nu_ref,
                  po_ref, muo_ref, nuo_ref, *, b1, b2, eps, wd):
    # scalars ride a (1, 4) SMEM ref: 2-D scalar blocks are the layout
    # Mosaic's SMEM path expects
    lr = scal_ref[0, 0]
    clip = scal_ref[0, 1]
    c1 = scal_ref[0, 2]       # 1 - b1^t
    c2 = scal_ref[0, 3]       # 1 - b2^t
    g = g_ref[:].astype(jnp.float32) * clip
    mu = b1 * mu_ref[:] + (1.0 - b1) * g
    nu = b2 * nu_ref[:] + (1.0 - b2) * g * g
    p = p_ref[:].astype(jnp.float32)
    update = lr * ((mu / c1) / (jnp.sqrt(nu / c2) + eps) + wd * p)
    po_ref[:] = (p - update).astype(po_ref.dtype)
    muo_ref[:] = mu
    nuo_ref[:] = nu


def _leaf_update(p, g, mu, nu, scalars, *, b1, b2, eps, wd,
                 use_pallas=True):
    n = p.size
    if use_pallas and n % (8 * _LANES) == 0 and not interpret_mode():
        rows = n // _LANES
        br = min(_ROWS, rows)
        if rows % br:
            br = 8  # rows is a multiple of 8 by the check above
        shape2d = (rows, _LANES)
        grid = (rows // br,)
        spec = lambda dt: pl.BlockSpec((br, _LANES), lambda i: (i, 0),
                                       memory_space=pltpu.VMEM)
        po, muo, nuo = pl.pallas_call(
            functools.partial(_adamw_kernel, b1=b1, b2=b2, eps=eps, wd=wd),
            grid=grid,
            in_specs=[
                pl.BlockSpec(memory_space=pltpu.SMEM),
                spec(p.dtype), spec(g.dtype),
                spec(jnp.float32), spec(jnp.float32),
            ],
            out_specs=[spec(p.dtype), spec(jnp.float32), spec(jnp.float32)],
            out_shape=[
                jax.ShapeDtypeStruct(shape2d, p.dtype),
                jax.ShapeDtypeStruct(shape2d, jnp.float32),
                jax.ShapeDtypeStruct(shape2d, jnp.float32),
            ],
            interpret=interpret_mode(),
        )(scalars, p.reshape(shape2d), g.reshape(shape2d),
          mu.reshape(shape2d), nu.reshape(shape2d))
        return (po.reshape(p.shape), muo.reshape(p.shape),
                nuo.reshape(p.shape))
    # jnp fallback: same math (odd-shaped leaves, CPU tests)
    lr, clip, c1, c2 = (scalars[0, 0], scalars[0, 1], scalars[0, 2],
                        scalars[0, 3])
    gf = g.astype(jnp.float32) * clip
    mu2 = b1 * mu + (1.0 - b1) * gf
    nu2 = b2 * nu + (1.0 - b2) * gf * gf
    pf = p.astype(jnp.float32)
    update = lr * ((mu2 / c1) / (jnp.sqrt(nu2 / c2) + eps) + wd * pf)
    return (pf - update).astype(p.dtype), mu2, nu2


class FusedAdamWState(NamedTuple):
    count: jax.Array
    mu: Any
    nu: Any


class FusedAdamW:
    """Drop-in for `optax.chain(clip_by_global_norm, adamw)` with a fused
    apply: `apply(grads, state, params) -> (new_params, new_state)` updates
    params directly (one memory pass) instead of returning deltas.
    `make_train_step` detects this interface."""

    def __init__(self, learning_rate: Union[float, Callable[[jax.Array], jax.Array]],
                 b1: float = 0.9, b2: float = 0.95, eps: float = 1e-8,
                 weight_decay: float = 0.1, clip_norm: float = 1.0):
        self.learning_rate = learning_rate
        self.b1, self.b2, self.eps = b1, b2, eps
        self.weight_decay = weight_decay
        self.clip_norm = clip_norm

    def init(self, params: Any) -> FusedAdamWState:
        zeros = jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params)
        return FusedAdamWState(count=jnp.zeros((), jnp.int32),
                               mu=zeros,
                               nu=jax.tree_util.tree_map(jnp.copy, zeros))

    def apply(self, grads: Any, state: FusedAdamWState, params: Any):
        import optax

        gnorm = optax.global_norm(grads)
        clip = jnp.minimum(1.0, self.clip_norm / jnp.maximum(gnorm, 1e-12))
        count = state.count + 1
        lr = (self.learning_rate(state.count)
              if callable(self.learning_rate) else self.learning_rate)
        t = count.astype(jnp.float32)
        scalars = jnp.stack([
            jnp.asarray(lr, jnp.float32),
            clip.astype(jnp.float32),
            1.0 - self.b1 ** t,
            1.0 - self.b2 ** t,
        ]).reshape(1, 4)
        leaves_p, tdef = jax.tree_util.tree_flatten(params)
        leaves_g = tdef.flatten_up_to(grads)
        leaves_mu = tdef.flatten_up_to(state.mu)
        leaves_nu = tdef.flatten_up_to(state.nu)
        big = set(sorted(range(len(leaves_p)),
                         key=lambda i: leaves_p[i].size,
                         reverse=True)[:PALLAS_LEAVES])
        out_p, out_mu, out_nu = [], [], []
        for i, (p, g, mu, nu) in enumerate(
                zip(leaves_p, leaves_g, leaves_mu, leaves_nu)):
            po, muo, nuo = _leaf_update(
                p, g, mu, nu, scalars, b1=self.b1, b2=self.b2, eps=self.eps,
                wd=self.weight_decay, use_pallas=i in big)
            out_p.append(po)
            out_mu.append(muo)
            out_nu.append(nuo)
        return (jax.tree_util.tree_unflatten(tdef, out_p),
                FusedAdamWState(count=count,
                                mu=jax.tree_util.tree_unflatten(tdef, out_mu),
                                nu=jax.tree_util.tree_unflatten(tdef, out_nu)))
