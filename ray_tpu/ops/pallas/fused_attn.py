"""Fused attention block (rmsnorm -> qkv -> rotary -> flash attention -> Wo
+ residual) as a custom_vjp — the attention-half twin of
`ops/pallas/fused_ffn.py`.

The win, as measured for the FFN half (BASELINE.md r05 note), is SAVING
instead of RECOMPUTING: under dots remat the backward re-runs the fp32
rotary, the [b,s,h,d]<->[b,h,s,d] transposes, and the whole flash forward
kernel to regenerate the attention output and softmax statistics. Here the
forward saves the post-rotary q/k (bf16), v, the attention output and the
flash kernel's logsumexp rows, so the backward goes straight to the flash
backward kernels (dq/dk/dv), un-rotates with the transposed rotation, and
finishes with plain XLA dW/dx matmuls + the rmsnorm VJP. Residual cost vs
the dots policy: ~+16 MB/layer at b1 shapes (covered by what fused_ffn
freed).

K/V are saved UNREPEATED ([b, kv_heads, s, hd]); GQA expansion happens at
kernel entry in both directions (XLA lowers the repeat to a broadcast), and
dk/dv are summed back over the repeat groups.

No reference counterpart: hellofinch/ray ships no kernels (SURVEY.md §2.4).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ray_tpu.ops.layers import apply_rotary
from ray_tpu.ops.pallas._util import on_tpu


def _repeat_kv(t: jax.Array, n_rep: int) -> jax.Array:
    if n_rep == 1:
        return t
    b, h, s, d = t.shape
    return jnp.broadcast_to(t[:, :, None], (b, h, n_rep, s, d)).reshape(
        b, h * n_rep, s, d)


def _use_kernel(s: int, hd: int) -> bool:
    return on_tpu() and hd >= 128 and s >= 128


def _core_fwd(q4, kr, vr, scale):
    """[b, h, s, hd] (k/v already GQA-repeated) -> (out [b,h,s,hd],
    lse [bh, 8, s] f32 or None on the reference path)."""
    from ray_tpu.ops.pallas.flash_attention import _flash_fwd

    b, h, s, hd = q4.shape
    if _use_kernel(s, hd):
        out, lse = _flash_fwd(q4.reshape(b * h, s, hd),
                              kr.reshape(b * h, s, hd),
                              vr.reshape(b * h, s, hd),
                              scale, True, min(1024, s), min(1024, s))
        return out.reshape(b, h, s, hd), lse
    from ray_tpu.ops.attention import causal_attention_reference

    out = causal_attention_reference(q4, kr, vr, sm_scale=scale, causal=True)
    return out, None


def _core_bwd(q4, kr, vr, out, lse, do4, scale):
    """Returns (dq4, dkr, dvr) in [b, h, s, hd]."""
    b, h, s, hd = q4.shape
    if lse is not None:
        from ray_tpu.ops.pallas.flash_attention import _flash_bwd

        dq, dk, dv = _flash_bwd(
            q4.reshape(b * h, s, hd), kr.reshape(b * h, s, hd),
            vr.reshape(b * h, s, hd), out.reshape(b * h, s, hd), lse,
            do4.reshape(b * h, s, hd), scale, True,
            min(1024, s), min(512, s))
        return (dq.reshape(b, h, s, hd), dk.reshape(b, h, s, hd),
                dv.reshape(b, h, s, hd))
    from ray_tpu.ops.attention import causal_attention_reference

    _, vjp = jax.vjp(
        lambda q, k, v: causal_attention_reference(q, k, v, sm_scale=scale,
                                                   causal=True), q4, kr, vr)
    return vjp(do4)


def _fwd_impl(x, nw, wq, wk, wv, wo, cos, sin, n_heads, n_kv_heads, eps):
    b, s, d = x.shape
    hd = d // n_heads
    xf = x.astype(jnp.float32)
    rstd = jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    h = (xf * rstd * nw.astype(jnp.float32)).astype(x.dtype)
    q = (h @ wq).reshape(b, s, n_heads, hd)
    k = (h @ wk).reshape(b, s, n_kv_heads, hd)
    v = (h @ wv).reshape(b, s, n_kv_heads, hd)
    q = apply_rotary(q, cos, sin).transpose(0, 2, 1, 3)   # [b, h, s, hd]
    k = apply_rotary(k, cos, sin).transpose(0, 2, 1, 3)
    v = v.transpose(0, 2, 1, 3)
    n_rep = n_heads // n_kv_heads
    scale = hd ** -0.5
    out, lse = _core_fwd(q, _repeat_kv(k, n_rep), _repeat_kv(v, n_rep), scale)
    attn_flat = out.transpose(0, 2, 1, 3).reshape(b, s, n_heads * hd)
    y = x + (attn_flat @ wo).astype(x.dtype)
    return y, (rstd, q, k, v, out, lse)


@functools.partial(jax.custom_vjp, nondiff_argnums=(8, 9, 10))
def attn_block(x: jax.Array, norm_w: jax.Array, wq: jax.Array, wk: jax.Array,
               wv: jax.Array, wo: jax.Array, cos: jax.Array, sin: jax.Array,
               n_heads: int, n_kv_heads: int, eps: float = 1e-5) -> jax.Array:
    """x [b, s, d] -> x + Wo(flash_attn(rotary(qkv(rmsnorm(x)))))."""
    y, _ = _fwd_impl(x, norm_w, wq, wk, wv, wo, cos, sin,
                     n_heads, n_kv_heads, eps)
    return y


def _vjp_fwd(x, norm_w, wq, wk, wv, wo, cos, sin, n_heads, n_kv_heads, eps):
    y, (rstd, q, k, v, out, lse) = _fwd_impl(
        x, norm_w, wq, wk, wv, wo, cos, sin, n_heads, n_kv_heads, eps)
    return y, (x, rstd, q, k, v, out, lse, norm_w, wq, wk, wv, wo, cos, sin)


def _vjp_bwd(n_heads, n_kv_heads, eps, res, dy):
    x, rstd, q, k, v, out, lse, nw, wq, wk, wv, wo, cos, sin = res
    b, s, d = x.shape
    hd = d // n_heads
    n_rep = n_heads // n_kv_heads
    scale = hd ** -0.5
    dy2d = dy.reshape(b * s, d)

    # output projection
    attn_flat = out.transpose(0, 2, 1, 3).reshape(b * s, n_heads * hd)
    dwo = jax.lax.dot_general(attn_flat, dy2d, (((0,), (0,)), ((), ())),
                              preferred_element_type=jnp.float32).astype(wo.dtype)
    do4 = (dy2d @ wo.T).reshape(b, s, n_heads, hd).transpose(0, 2, 1, 3)
    do4 = do4.astype(out.dtype)

    # flash backward on saved tensors (no forward re-run)
    dq4, dkr, dvr = _core_bwd(q, _repeat_kv(k, n_rep), _repeat_kv(v, n_rep),
                              out, lse, do4, scale)
    if n_rep > 1:
        dkr = dkr.reshape(b, n_kv_heads, n_rep, s, hd).sum(axis=2)
        dvr = dvr.reshape(b, n_kv_heads, n_rep, s, hd).sum(axis=2)

    # un-rotate: the rotation is orthogonal, so the VJP is rotation by -θ
    dq_pre = apply_rotary(dq4.transpose(0, 2, 1, 3), cos, -sin)
    dk_pre = apply_rotary(dkr.transpose(0, 2, 1, 3), cos, -sin)
    dv_pre = dvr.transpose(0, 2, 1, 3)
    dq2d = dq_pre.reshape(b * s, n_heads * hd).astype(x.dtype)
    dk2d = dk_pre.reshape(b * s, n_kv_heads * hd).astype(x.dtype)
    dv2d = dv_pre.reshape(b * s, n_kv_heads * hd).astype(x.dtype)

    # dW for the three projections; h recomputed elementwise (one pass)
    x2d = x.reshape(b * s, d)
    rstd2d = rstd.reshape(b * s, 1)
    h2d = (x2d.astype(jnp.float32) * rstd2d
           * nw.astype(jnp.float32)).astype(x.dtype)
    ct = (((0,), (0,)), ((), ()))
    dwq = jax.lax.dot_general(h2d, dq2d, ct,
                              preferred_element_type=jnp.float32).astype(wq.dtype)
    dwk = jax.lax.dot_general(h2d, dk2d, ct,
                              preferred_element_type=jnp.float32).astype(wk.dtype)
    dwv = jax.lax.dot_general(h2d, dv2d, ct,
                              preferred_element_type=jnp.float32).astype(wv.dtype)

    # dh back through the projections, then the rmsnorm VJP
    dh = (jax.lax.dot_general(dq2d, wq, (((1,), (1,)), ((), ())),
                              preferred_element_type=jnp.float32)
          + jax.lax.dot_general(dk2d, wk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
          + jax.lax.dot_general(dv2d, wv, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32))
    xf = x2d.astype(jnp.float32)
    wdh = dh * nw.astype(jnp.float32)
    proj = jnp.sum(wdh * xf, axis=-1, keepdims=True) / d
    dx = (rstd2d * (wdh - xf * rstd2d * rstd2d * proj)
          + dy2d.astype(jnp.float32)).astype(x.dtype).reshape(b, s, d)
    dnw = jnp.sum(dh * xf * rstd2d, axis=0).astype(nw.dtype)
    return (dx, dnw, dwq, dwk, dwv, dwo,
            jnp.zeros_like(cos), jnp.zeros_like(sin))


attn_block.defvjp(_vjp_fwd, _vjp_bwd)
