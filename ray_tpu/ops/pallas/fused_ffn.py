"""Fused FFN block (rmsnorm -> gate/up -> swiglu -> down + residual) with a
hand-written Pallas backward.

Why: BASELINE.md's r04 decomposition pinned the b1 MFU gap on backward-pass
elementwise HBM traffic under dots remat — XLA's backward materializes the
swiglu recompute, d_swiglu, and the re-normed hidden states as separate HBM
round-trips between the dW/dx matmuls. Here the backward is four Pallas
matmul kernels whose prologues/epilogues compute those elementwise chains
on tiles already resident in VMEM:

  K1  dW_down = swiglu(gate, up)^T @ dy          (swiglu fused as prologue)
  K2  d_s = dy @ W_down^T ->                     (never hits HBM)
      dgate = d_s * up * silu'(gate), dup = d_s * silu(gate)
  K3  dW_gate = h^T @ dgate, dW_up = h^T @ dup   (h = x*rstd*nw recomputed
                                                  as prologue, never stored)
  (dh = dgate @ Wg^T + dup @ Wu^T and the rmsnorm VJP stay XLA — see the
   note at the call-site: a Pallas variant re-read the weight panels per
   row block and lost more than its fusion saved.)

The forward stays plain XLA (it already runs at ~93% of ideal). Residuals
saved — x, rstd, gate, up — are the same set the `dots` remat policy keeps,
so memory is unchanged; the block must sit OUTSIDE any jax.checkpoint
region (a custom_vjp inside remat would have its forward replayed to
regenerate residuals, re-running all three matmuls).

No reference counterpart: hellofinch/ray ships no kernels (SURVEY.md §2.4).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ray_tpu.ops.pallas._util import interpret_mode

# Tile sizes: 512 keeps the MXU busy with full 128-lane tiles while the
# double-buffered operands of the widest kernel (K4's [d, bk] weight tiles)
# stay inside the ~16 MB VMEM budget.
_BM = 512
_BN = 512
_BK = 512

# Per-kernel toggles (trace-time): each Pallas kernel has a semantically
# identical XLA fallback in _vjp_bwd, so step-time attribution is a flag
# flip + re-jit. Measured on v5e at b1 shapes (batch 2 x 2048, d=2048,
# dff=8192), step time vs the all-XLA custom backward's 243.2 ms:
#   K1 pallas +16.0 ms at 512^3 tiles and +18.6 ms with full-d N blocks
#   (the retile removed the gate/up panel re-reads but multiplied the dy
#   panel re-reads; both lose to XLA), K2 pallas +8.9 ms,
#   K3 pallas -6.3 ms (the h-recompute prologue + two dots sharing one
#   operand panel beat XLA's materialize-then-matmul).
# Defaults = the measured winners. NOTE the custom_vjp itself is the main
# win: saving gate/up and hand-writing the backward beats autodiff under
# dots remat by ~7 ms even with every kernel on XLA.
USE_K1 = False
USE_K2 = False
USE_K3 = True


def _silu(x):
    return x * jax.nn.sigmoid(x)


def _dsilu(x):
    s = jax.nn.sigmoid(x)
    return s * (1.0 + x * (1.0 - s))


# ------------------------------------------------------------------ kernels


def _dw_down_kernel(gate_ref, up_ref, dy_ref, out_ref, acc_ref):
    """out[dff, d] += swiglu(gate, up)[t, dff]^T @ dy[t, d]; grid (i, k),
    k (= token blocks) innermost, full-d output rows per block."""
    k = pl.program_id(1)

    @pl.when(k == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    g = gate_ref[:].astype(jnp.float32)
    u = up_ref[:].astype(jnp.float32)
    s = (_silu(g) * u).astype(dy_ref.dtype)          # [bk, bm]
    acc_ref[:] += jax.lax.dot_general(
        s, dy_ref[:], (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)           # [bm, bn]

    @pl.when(k == pl.num_programs(1) - 1)
    def _():
        out_ref[:] = acc_ref[:].astype(out_ref.dtype)


def _dgateup_kernel(dy_ref, wd_ref, gate_ref, up_ref, dgate_ref, dup_ref,
                    acc_ref):
    """d_s = dy[t, d] @ W_down[dff, d]^T accumulated over d blocks (k
    innermost); at the last k step the swiglu VJP runs on the VMEM tile and
    only dgate/dup are written — d_s never exists in HBM."""
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    acc_ref[:] += jax.lax.dot_general(
        dy_ref[:], wd_ref[:], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)           # [bm, bn]

    @pl.when(k == pl.num_programs(2) - 1)
    def _():
        ds = acc_ref[:]
        g = gate_ref[:].astype(jnp.float32)
        u = up_ref[:].astype(jnp.float32)
        dgate_ref[:] = (ds * u * _dsilu(g)).astype(dgate_ref.dtype)
        dup_ref[:] = (ds * _silu(g)).astype(dup_ref.dtype)


def _dw_gateup_kernel(x_ref, rstd_ref, nw_ref, dgate_ref, dup_ref,
                      dwg_ref, dwu_ref, accg_ref, accu_ref):
    """dW_gate/dW_up = h^T @ dgate/dup with h = (x * rstd * nw) recomputed
    per tile (the normed hidden state is never stored)."""
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _():
        accg_ref[:] = jnp.zeros_like(accg_ref)
        accu_ref[:] = jnp.zeros_like(accu_ref)

    h = (x_ref[:].astype(jnp.float32) * rstd_ref[:]
         * nw_ref[:].astype(jnp.float32)).astype(dgate_ref.dtype)  # [bk, bm]
    accg_ref[:] += jax.lax.dot_general(
        h, dgate_ref[:], (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    accu_ref[:] += jax.lax.dot_general(
        h, dup_ref[:], (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(k == pl.num_programs(2) - 1)
    def _():
        dwg_ref[:] = accg_ref[:].astype(dwg_ref.dtype)
        dwu_ref[:] = accu_ref[:].astype(dwu_ref.dtype)


# ------------------------------------------------------------- entry points


def _fwd_impl(x2d, nw, wg, wu, wd, eps):
    xf = x2d.astype(jnp.float32)
    rstd = jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    h = (xf * rstd * nw.astype(jnp.float32)).astype(x2d.dtype)
    gate = h @ wg
    up = h @ wu
    out = (_silu(gate.astype(jnp.float32)).astype(x2d.dtype) * up) @ wd
    return x2d + out.astype(x2d.dtype), rstd, gate, up


@functools.partial(jax.custom_vjp, nondiff_argnums=(5,))
def ffn_block(x: jax.Array, norm_w: jax.Array, w_gate: jax.Array,
              w_up: jax.Array, w_down: jax.Array, eps: float = 1e-5) -> jax.Array:
    """x [..., d] -> x + W_down(swiglu(Wg(rmsnorm(x)), Wu(rmsnorm(x))))."""
    shape = x.shape
    y, _, _, _ = _fwd_impl(x.reshape(-1, shape[-1]), norm_w, w_gate, w_up,
                           w_down, eps)
    return y.reshape(shape)


def _vjp_fwd(x, norm_w, w_gate, w_up, w_down, eps):
    shape = x.shape
    x2d = x.reshape(-1, shape[-1])
    y, rstd, gate, up = _fwd_impl(x2d, norm_w, w_gate, w_up, w_down, eps)
    return y.reshape(shape), (x2d, rstd, gate, up, norm_w, w_gate, w_up,
                              w_down, shape)


def _vjp_bwd(eps, res, dy):
    x2d, rstd, gate, up, nw, wg, wu, wd, shape = res
    d = shape[-1]
    dy2d = dy.reshape(-1, d)
    T = x2d.shape[0]
    dff = wg.shape[1]
    interp = interpret_mode()

    bm, bn, bk = min(_BM, dff), min(_BN, d), min(_BK, T)
    # The tiling constraint belongs to the Pallas kernels only: with every
    # USE_K* flag turned off the backward is pure XLA and accepts any
    # (T, d, dff) — rejecting non-tiling shapes at trace time used to break
    # the all-XLA configuration for no reason. (USE_K3 defaults on, so the
    # guard still fires out of the box.)
    if (USE_K1 or USE_K2 or USE_K3) and (T % bk or dff % bm or d % bn):
        raise ValueError(f"fused_ffn: shapes ({T}, {d}, {dff}) must tile by "
                         f"({bk}, {bn}, {bm}) when a Pallas kernel "
                         f"(USE_K1/K2/K3) is enabled")

    # K1: dW_down [dff, d]. Full-d N blocks: the gate/up operand panels
    # are fetched exactly once (the 512x512x512 variant re-read them per
    # N block — +16 ms; this layout's only repeat is dy, dff/bm x 16 MB).
    if not USE_K1:
        s_act = (_silu(gate.astype(jnp.float32))
                 * up.astype(jnp.float32)).astype(gate.dtype)
        dwd = jax.lax.dot_general(
            s_act, dy2d, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(wd.dtype)
    else:
      bm1, bk1 = min(256, dff), min(_BK, T)
      dwd = pl.pallas_call(
        _dw_down_kernel,
        grid=(dff // bm1, T // bk1),
        in_specs=[
            pl.BlockSpec((bk1, bm1), lambda i, k: (k, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((bk1, bm1), lambda i, k: (k, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((bk1, d), lambda i, k: (k, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((bm1, d), lambda i, k: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((dff, d), wd.dtype),
        scratch_shapes=[pltpu.VMEM((bm1, d), jnp.float32)],
        interpret=interp,
      )(gate, up, dy2d)

    # K2: dgate/dup [T, dff]
    bm2, bn2, bk2 = min(_BM, T), min(_BN, dff), min(_BK, d)
    if not USE_K2:
        ds = jax.lax.dot_general(dy2d, wd, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        gf = gate.astype(jnp.float32)
        uf = up.astype(jnp.float32)
        dgate = (ds * uf * _dsilu(gf)).astype(gate.dtype)
        dup = (ds * _silu(gf)).astype(up.dtype)
    else:
      dgate, dup = pl.pallas_call(
        _dgateup_kernel,
        grid=(T // bm2, dff // bn2, d // bk2),
        in_specs=[
            pl.BlockSpec((bm2, bk2), lambda i, j, k: (i, k), memory_space=pltpu.VMEM),
            pl.BlockSpec((bn2, bk2), lambda i, j, k: (j, k), memory_space=pltpu.VMEM),
            pl.BlockSpec((bm2, bn2), lambda i, j, k: (i, j), memory_space=pltpu.VMEM),
            pl.BlockSpec((bm2, bn2), lambda i, j, k: (i, j), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((bm2, bn2), lambda i, j, k: (i, j), memory_space=pltpu.VMEM),
            pl.BlockSpec((bm2, bn2), lambda i, j, k: (i, j), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((T, dff), gate.dtype),
            jax.ShapeDtypeStruct((T, dff), up.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((bm2, bn2), jnp.float32)],
        interpret=interp,
      )(dy2d, wd, gate, up)

    # K3: dW_gate/dW_up [d, dff]
    bm3, bn3, bk3 = min(_BM, d), min(_BN, dff), min(_BK, T)
    if not USE_K3:
        h = (x2d.astype(jnp.float32) * rstd
             * nw.astype(jnp.float32)).astype(x2d.dtype)
        dwg = jax.lax.dot_general(h, dgate, (((0,), (0,)), ((), ())),
                                  preferred_element_type=jnp.float32).astype(wg.dtype)
        dwu = jax.lax.dot_general(h, dup, (((0,), (0,)), ((), ())),
                                  preferred_element_type=jnp.float32).astype(wu.dtype)
    else:
      dwg, dwu = pl.pallas_call(
        _dw_gateup_kernel,
        grid=(d // bm3, dff // bn3, T // bk3),
        in_specs=[
            pl.BlockSpec((bk3, bm3), lambda i, j, k: (k, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((bk3, 1), lambda i, j, k: (k, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bm3), lambda i, j, k: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((bk3, bn3), lambda i, j, k: (k, j), memory_space=pltpu.VMEM),
            pl.BlockSpec((bk3, bn3), lambda i, j, k: (k, j), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((bm3, bn3), lambda i, j, k: (i, j), memory_space=pltpu.VMEM),
            pl.BlockSpec((bm3, bn3), lambda i, j, k: (i, j), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((d, dff), wg.dtype),
            jax.ShapeDtypeStruct((d, dff), wu.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((bm3, bn3), jnp.float32),
                        pltpu.VMEM((bm3, bn3), jnp.float32)],
        interpret=interp,
      )(x2d, rstd, nw.reshape(1, -1), dgate, dup)

    # Step 4 — dh matmuls + rmsnorm VJP — stays XLA: a measured Pallas
    # variant (full-d N blocks so the VJP row-reduction fits one tile) had
    # to re-read the [d, dff] weight panels once per 128-row block, ~2 GB
    # of extra HBM traffic per layer, and lost more than the elementwise
    # fusion saved. XLA tiles the matmul properly and fuses the elementwise
    # VJP chain into one pass over dh.
    dh = (jax.lax.dot_general(dgate, wg, (((1,), (1,)), ((), ())),
                              preferred_element_type=jnp.float32)
          + jax.lax.dot_general(dup, wu, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32))
    xf = x2d.astype(jnp.float32)
    wdh = dh * nw.astype(jnp.float32)
    proj = jnp.sum(wdh * xf, axis=-1, keepdims=True) / d
    dx = (rstd * (wdh - xf * rstd * rstd * proj)
          + dy2d.astype(jnp.float32)).astype(x2d.dtype)
    dnw = jnp.sum(dh * xf * rstd, axis=0).astype(nw.dtype)
    return dx.reshape(shape), dnw, dwg, dwu, dwd


ffn_block.defvjp(_vjp_fwd, _vjp_bwd)
