"""Flash attention (online-softmax) Pallas kernels, forward AND backward.

Forward kernel with O(seq) memory: the [sq, sk] score matrix never hits
HBM. Grid = (batch*heads, q_blocks, k_blocks) with the k axis innermost —
sequential on TPU — so a VMEM accumulator carries the running max / sum /
weighted values across k blocks (the standard online-softmax recurrence).
The forward also emits the per-row logsumexp so the backward can recompute
attention probabilities blockwise.

Backward is the FlashAttention-2 recompute scheme as two fused kernels —
O(seq) memory, no [sq, sk] materialization:
  * dk/dv kernel: grid (bh, k_blocks, q_blocks), q innermost; for each key
    block accumulate  dv += pᵀ·dO  and  dk += dsᵀ·q  across query blocks.
  * dq kernel: grid (bh, q_blocks, k_blocks), k innermost; accumulate
    dq += ds·k  across key blocks.
with  p = exp(q·kᵀ·scale − lse)  recomputed from the saved logsumexp and
ds = p·(dO·vᵀ − Δ)·scale,  Δ = rowsum(dO ⊙ O)  precomputed outside.

This kernel pair is the training hot path (`ray_tpu.ops.attention` routes
TPU training through it). No reference-counterpart: hellofinch/ray
delegates all device math to torch (SURVEY.md §2.4).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ray_tpu.ops.pallas._util import cdiv, interpret_mode

_NEG_INF = -1e30


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref, *,
                sm_scale: float, causal: bool, block_q: int, block_k: int,
                sq: int, sk: int, qdim: int = 1, kdim: int = 2):
    i_q = pl.program_id(qdim)
    i_k = pl.program_id(kdim)
    n_k = pl.num_programs(kdim)

    @pl.when(i_k == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    # Causal: row r attends keys <= r + (sk - sq) (diagonal offset aligns
    # the query window to the END of the key axis — the KV-cache decode
    # convention, matching _reference's tril(k=sk-sq)). Skip k blocks
    # entirely above the band.
    offset = sk - sq
    should_compute = True
    if causal:
        should_compute = (
            i_k * block_k <= i_q * block_q + block_q - 1 + offset)

    @pl.when(should_compute)
    def _compute():
        # Matmul operands stay in the input dtype (bf16 in training): the MXU
        # runs bf16×bf16→f32 at full rate, f32×f32 at a fraction of it. All
        # accumulation and softmax state is f32.
        q = q_ref[0]                      # [bq, d]
        k = k_ref[0]                      # [bk, d]
        v = v_ref[0]                      # [bk, d]
        # zero v's padded tail rows: their p weights are 0, but 0*garbage
        # (NaN in interpret mode) would still poison the p@v accumulate
        v_rows = jax.lax.broadcasted_iota(jnp.int32, v.shape, 0) + i_k * block_k
        v = jnp.where(v_rows < sk, v, jnp.zeros_like(v))
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale  # [bq, bk] f32
        cols = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1) + i_k * block_k
        if causal:
            rows = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0) + i_q * block_q
            s = jnp.where(cols <= rows + offset, s, _NEG_INF)
        # mask the padded key tail of the last block (sk % block_k != 0)
        s = jnp.where(cols < sk, s, _NEG_INF)

        m_prev = m_ref[:]                       # [bq, 1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                  # [bq, bk] f32
        alpha = jnp.exp(m_prev - m_new)         # rescale old state
        l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jnp.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32)
        m_ref[:] = m_new

    @pl.when(i_k == n_k - 1)
    def _finalize():
        # Fully-masked rows (can't happen for causal self-attn) guard: l>=1e-30.
        l = jnp.maximum(l_ref[:], 1e-30)
        o_ref[0] = (acc_ref[:] / l).astype(o_ref.dtype)
        # lse layout is [bh, 8, sq] (8 broadcast sublanes) so its block's
        # trailing dims satisfy Mosaic's (8,128) tiling; see _flash_fwd.
        lse_ref[0] = jnp.broadcast_to(
            (m_ref[:] + jnp.log(l))[:, 0][None, :], lse_ref.shape[1:])


def _flash_fwd(q, k, v, sm_scale, causal, block_q, block_k):
    """Returns (out, lse); lse is [bh, 8, sq] float32 — m + log(l) per row,
    broadcast across 8 sublanes so the (1, 8, bq) block satisfies Mosaic's
    trailing-(8, 128) tiling requirement (cf. the MIN_BLOCK padding in JAX's
    own TPU flash kernel)."""
    bh, sq, d = q.shape
    sk = k.shape[1]
    bq = min(block_q, sq)
    bk = min(block_k, sk)
    grid = (bh, cdiv(sq, bq), cdiv(sk, bk))
    return pl.pallas_call(
        functools.partial(_fwd_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=bq, block_k=bk, sq=sq, sk=sk),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 8, bq), lambda b, i, j: (b, 0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, 8, sq), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
        ],
        interpret=interpret_mode(),
    )(q, k, v)


def _recompute_p_ds(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *,
                    i_q, i_k, sm_scale, causal, block_q, block_k, sq, sk):
    """Shared backward-block math: recompute p [bq,bk] and ds [bq,bk]."""
    # Operands stay in the input dtype (bf16 in training) for full-rate MXU;
    # p/ds are computed f32 and cast back at the accumulating matmuls.
    q = q_ref[0]                              # [bq, d]
    k = k_ref[0]                              # [bk, d]
    v = v_ref[0]                              # [bk, d]
    do = do_ref[0]                            # [bq, d]
    lse = lse_ref[0][0, :][:, None]           # [8, bq] sublane 0 -> [bq, 1]
    delta = delta_ref[0][0, :][:, None]       # [bq, 1]
    offset = sk - sq
    # Zero every operand's padded tail rows: the contraction dims of dsᵀ·q,
    # ds·k and pᵀ·dO run over them, and although p/ds are 0 there, garbage
    # (NaN in interpret mode) still poisons the dot because 0·NaN = NaN.
    q_rows = jax.lax.broadcasted_iota(jnp.int32, q.shape, 0) + i_q * block_q
    q = jnp.where(q_rows < sq, q, jnp.zeros_like(q))
    do = jnp.where(q_rows < sq, do, jnp.zeros_like(do))
    k_rows = jax.lax.broadcasted_iota(jnp.int32, k.shape, 0) + i_k * block_k
    k = jnp.where(k_rows < sk, k, jnp.zeros_like(k))
    v = jnp.where(k_rows < sk, v, jnp.zeros_like(v))
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * sm_scale        # [bq, bk] f32
    rows = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0) + i_q * block_q
    cols = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1) + i_k * block_k
    valid = (rows < sq) & (cols < sk)
    if causal:
        valid &= cols <= rows + offset
    p = jnp.where(valid, jnp.exp(s - lse), 0.0)               # [bq, bk] f32
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)                   # [bq, bk] f32
    # where(): p==0 at invalid entries but dp can be NaN/garbage there
    # (padded v columns), and 0*NaN = NaN.
    ds = jnp.where(valid, p * (dp - delta) * sm_scale, 0.0)   # [bq, bk] f32
    return q, k, do, p, ds


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc, *,
                    sm_scale, causal, block_q, block_k, sq, sk,
                    kdim: int = 1, qdim: int = 2, n_qb: int | None = None):
    """When n_qb is given (packed GQA layout), the innermost grid axis
    enumerates e = r * n_qb + i_q over the n_rep query heads sharing this
    key/value head — dk/dv accumulate across all of them."""
    i_k = pl.program_id(kdim)
    e = pl.program_id(qdim)
    n_e = pl.num_programs(qdim)
    i_q = e if n_qb is None else e % n_qb

    @pl.when(e == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    offset = sk - sq
    should_compute = True
    if causal:  # key block entirely above the causal band contributes nothing
        should_compute = (
            i_k * block_k <= i_q * block_q + block_q - 1 + offset)

    @pl.when(should_compute)
    def _compute():
        q, k, do, p, ds = _recompute_p_ds(
            q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
            i_q=i_q, i_k=i_k, sm_scale=sm_scale, causal=causal,
            block_q=block_q, block_k=block_k, sq=sq, sk=sk)
        # dv += pᵀ·dO ; dk += dsᵀ·q   (contract over the q dimension)
        dv_acc[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dk_acc[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(e == n_e - 1)
    def _finalize():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, dq_acc, *,
                   sm_scale, causal, block_q, block_k, sq, sk,
                   qdim: int = 1, kdim: int = 2):
    i_q = pl.program_id(qdim)
    i_k = pl.program_id(kdim)
    n_k = pl.num_programs(kdim)

    @pl.when(i_k == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    offset = sk - sq
    should_compute = True
    if causal:
        should_compute = (
            i_k * block_k <= i_q * block_q + block_q - 1 + offset)

    @pl.when(should_compute)
    def _compute():
        q, k, do, p, ds = _recompute_p_ds(
            q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
            i_q=i_q, i_k=i_k, sm_scale=sm_scale, causal=causal,
            block_q=block_q, block_k=block_k, sq=sq, sk=sk)
        dq_acc[:] += jnp.dot(ds.astype(k.dtype), k,
                             preferred_element_type=jnp.float32)

    @pl.when(i_k == n_k - 1)
    def _finalize():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _flash_bwd(q, k, v, o, lse, do, sm_scale, causal, block_q, block_k):
    bh, sq, d = q.shape
    sk = k.shape[1]
    bq = min(block_q, sq)
    bk = min(block_k, sk)
    n_qb = cdiv(sq, bq)
    n_kb = cdiv(sk, bk)
    # Δ = rowsum(dO ⊙ O): tiny elementwise reduce; XLA fuses it, no kernel
    # needed (FlashAttention-2 preprocess step). Same [bh, 8, sq] broadcast
    # layout as lse (Mosaic trailing-dim tiling).
    delta = jnp.broadcast_to(
        jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                axis=-1)[:, None, :], (bh, 8, sq))

    kw = dict(sm_scale=sm_scale, causal=causal, block_q=bq, block_k=bk,
              sq=sq, sk=sk)
    qspec = pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0), memory_space=pltpu.VMEM)
    rowspec = pl.BlockSpec((1, 8, bq), lambda b, i, j: (b, 0, i), memory_space=pltpu.VMEM)

    # dk/dv: key blocks in the 2nd grid dim, query blocks innermost.
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, **kw),
        grid=(bh, n_kb, n_qb),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, jk, iq: (b, iq, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, d), lambda b, jk, iq: (b, jk, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, d), lambda b, jk, iq: (b, jk, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bq, d), lambda b, jk, iq: (b, iq, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 8, bq), lambda b, jk, iq: (b, 0, iq), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 8, bq), lambda b, jk, iq: (b, 0, iq), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, d), lambda b, jk, iq: (b, jk, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, d), lambda b, jk, iq: (b, jk, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sk, d), k.dtype),
            jax.ShapeDtypeStruct((bh, sk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ],
        interpret=interpret_mode(),
    )(q, k, v, do, lse, delta)

    # dq: query blocks in the 2nd grid dim, key blocks innermost.
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, **kw),
        grid=(bh, n_qb, n_kb),
        in_specs=[
            qspec,
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0), memory_space=pltpu.VMEM),
            qspec,
            rowspec,
            rowspec,
        ],
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        interpret=interpret_mode(),
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


def _reference(q, k, v, sm_scale, causal):
    logits = jnp.einsum("bqd,bkd->bqk", q, k).astype(jnp.float32) * sm_scale
    if causal:
        sq, sk = q.shape[1], k.shape[1]
        mask = jnp.tril(jnp.ones((sq, sk), dtype=bool), k=sk - sq)
        logits = jnp.where(mask, logits, _NEG_INF)
    p = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bqk,bkd->bqd", p, v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def flash_attention_pallas(q: jax.Array, k: jax.Array, v: jax.Array,
                           sm_scale: float | None = None, causal: bool = True,
                           block_q: int = 256, block_k: int = 256,
                           block_q_bwd: int | None = None,
                           block_k_bwd: int | None = None) -> jax.Array:
    """Flash attention over [batch*heads, seq, head_dim] tensors.

    The forward and backward kernels have different optimal tilings (the
    fwd kernel's VMEM working set is one q-block accumulator; the bwd dkv
    kernel carries two k-block accumulators), so block sizes can be given
    per direction; bwd defaults to the fwd blocks."""
    scale = sm_scale if sm_scale is not None else 1.0 / (q.shape[-1] ** 0.5)
    out, _lse = _flash_fwd(q, k, v, scale, causal, block_q, block_k)
    return out


def _vjp_fwd(q, k, v, sm_scale, causal, block_q, block_k,
             block_q_bwd, block_k_bwd):
    scale = sm_scale if sm_scale is not None else 1.0 / (q.shape[-1] ** 0.5)
    out, lse = _flash_fwd(q, k, v, scale, causal, block_q, block_k)
    return out, (q, k, v, out, lse)


def _vjp_bwd(sm_scale, causal, block_q, block_k, block_q_bwd, block_k_bwd,
             res, g):
    q, k, v, out, lse = res
    scale = sm_scale if sm_scale is not None else 1.0 / (q.shape[-1] ** 0.5)
    return _flash_bwd(q, k, v, out, lse, g, scale, causal,
                      block_q_bwd or block_q, block_k_bwd or block_k)


flash_attention_pallas.defvjp(_vjp_fwd, _vjp_bwd)


# ------------------------------------------------------- packed layout
# Same kernels over [batch, seq, heads*head_dim] operands — the layout the
# q/k/v projections naturally produce and the output projection consumes.
# The head axis becomes a grid dimension whose index maps pick the head's
# column slice, so the [b,s,h,d]<->[b,h,s,d] transposes disappear, and GQA
# is an index-map division (each group of n_rep query heads reads the same
# k/v head) instead of a materialized jnp.repeat.


def _flash_fwd_packed(q, k, v, n_heads, n_kv, sm_scale, causal,
                      block_q, block_k):
    b, sq, hd = q.shape
    d = hd // n_heads
    sk = k.shape[1]
    n_rep = n_heads // n_kv
    bq = min(block_q, sq)
    bk = min(block_k, sk)
    grid = (b, n_heads, cdiv(sq, bq), cdiv(sk, bk))
    return pl.pallas_call(
        functools.partial(_fwd_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=bq, block_k=bk, sq=sq, sk=sk,
                          qdim=2, kdim=3),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, h, i, j: (b, i, h),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, d), lambda b, h, i, j: (b, j, h // n_rep),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, d), lambda b, h, i, j: (b, j, h // n_rep),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda b, h, i, j: (b, i, h),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 8, bq), lambda b, h, i, j: (b, h, i),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, sq, hd), q.dtype),
            jax.ShapeDtypeStruct((b, 8 * n_heads, sq), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
        ],
        interpret=interpret_mode(),
    )(q, k, v)


def _flash_bwd_packed(q, k, v, o, lse, do, n_heads, n_kv, sm_scale, causal,
                      block_q, block_k):
    b, sq, hd = q.shape
    d = hd // n_heads
    sk = k.shape[1]
    n_rep = n_heads // n_kv
    bq = min(block_q, sq)
    bk = min(block_k, sk)
    n_qb = cdiv(sq, bq)
    n_kb = cdiv(sk, bk)

    # Δ = per-head rowsum(dO ⊙ O) in the [b, 8*heads, sq] broadcast layout.
    prod = (do.astype(jnp.float32) * o.astype(jnp.float32)).reshape(
        b, sq, n_heads, d).sum(-1)                       # [b, sq, h]
    delta = jnp.broadcast_to(
        prod.transpose(0, 2, 1)[:, :, None, :],          # [b, h, 1, sq]
        (b, n_heads, 8, sq)).reshape(b, 8 * n_heads, sq)

    kw = dict(sm_scale=sm_scale, causal=causal, block_q=bq, block_k=bk,
              sq=sq, sk=sk)
    rowspec_q = pl.BlockSpec((1, 8, bq), lambda b, h, i, j: (b, h, i),
                             memory_space=pltpu.VMEM)

    # dk/dv: one pass per kv head; the innermost axis enumerates
    # e = r * n_qb + i_q over this kv head's n_rep query heads.
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, **kw, kdim=2, qdim=3, n_qb=n_qb),
        grid=(b, n_kv, n_kb, n_rep * n_qb),
        in_specs=[
            pl.BlockSpec((1, bq, d),
                         lambda b, g, jk, e: (b, e % n_qb, g * n_rep + e // n_qb),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, d), lambda b, g, jk, e: (b, jk, g),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, d), lambda b, g, jk, e: (b, jk, g),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bq, d),
                         lambda b, g, jk, e: (b, e % n_qb, g * n_rep + e // n_qb),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 8, bq),
                         lambda b, g, jk, e: (b, g * n_rep + e // n_qb, e % n_qb),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 8, bq),
                         lambda b, g, jk, e: (b, g * n_rep + e // n_qb, e % n_qb),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, d), lambda b, g, jk, e: (b, jk, g),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, d), lambda b, g, jk, e: (b, jk, g),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, sk, n_kv * d), k.dtype),
            jax.ShapeDtypeStruct((b, sk, n_kv * d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ],
        interpret=interpret_mode(),
    )(q, k, v, do, lse, delta)

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, **kw, qdim=2, kdim=3),
        grid=(b, n_heads, n_qb, n_kb),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, h, i, j: (b, i, h),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, d), lambda b, h, i, j: (b, j, h // n_rep),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, d), lambda b, h, i, j: (b, j, h // n_rep),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bq, d), lambda b, h, i, j: (b, i, h),
                         memory_space=pltpu.VMEM),
            rowspec_q,
            rowspec_q,
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda b, h, i, j: (b, i, h),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b, sq, hd), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        interpret=interpret_mode(),
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9, 10))
def flash_attention_packed(q: jax.Array, k: jax.Array, v: jax.Array,
                           n_heads: int, n_kv_heads: int,
                           sm_scale: float | None = None, causal: bool = True,
                           block_q: int = 1024, block_k: int = 1024,
                           block_q_bwd: int | None = 1024,
                           block_k_bwd: int | None = 512) -> jax.Array:
    """Flash attention over packed [batch, seq, heads*head_dim] tensors.

    q: [b, s, n_heads*d]; k/v: [b, s, n_kv_heads*d]. Returns [b, s,
    n_heads*d]. Avoids the head transpose entirely and keeps GQA k/v
    unexpanded (the kernel's index maps route n_rep query heads to one
    kv head)."""
    if q.shape[-1] % n_heads or n_heads % n_kv_heads:
        raise ValueError(
            f"packed width {q.shape[-1]} must divide by n_heads={n_heads}, "
            f"which must divide by n_kv_heads={n_kv_heads}")
    d = q.shape[-1] // n_heads
    if k.shape[-1] != n_kv_heads * d:
        raise ValueError(f"k width {k.shape[-1]} != n_kv_heads*head_dim "
                         f"{n_kv_heads * d}")
    scale = sm_scale if sm_scale is not None else 1.0 / (d ** 0.5)
    out, _ = _flash_fwd_packed(q, k, v, n_heads, n_kv_heads, scale, causal,
                               block_q, block_k)
    return out


def _vjp_fwd_packed(q, k, v, n_heads, n_kv_heads, sm_scale, causal,
                    block_q, block_k, block_q_bwd, block_k_bwd):
    d = q.shape[-1] // n_heads
    scale = sm_scale if sm_scale is not None else 1.0 / (d ** 0.5)
    out, lse = _flash_fwd_packed(q, k, v, n_heads, n_kv_heads, scale, causal,
                                 block_q, block_k)
    return out, (q, k, v, out, lse)


def _vjp_bwd_packed(n_heads, n_kv_heads, sm_scale, causal, block_q, block_k,
                    block_q_bwd, block_k_bwd, res, g):
    q, k, v, out, lse = res
    d = q.shape[-1] // n_heads
    scale = sm_scale if sm_scale is not None else 1.0 / (d ** 0.5)
    return _flash_bwd_packed(q, k, v, out, lse, g, n_heads, n_kv_heads,
                             scale, causal,
                             block_q_bwd or block_q, block_k_bwd or block_k)


flash_attention_packed.defvjp(_vjp_fwd_packed, _vjp_bwd_packed)
