"""Flash attention (online-softmax) Pallas kernel.

Forward kernel with O(seq) memory: the [sq, sk] score matrix never hits
HBM. Grid = (batch*heads, q_blocks, k_blocks) with the k axis innermost —
sequential on TPU — so a VMEM accumulator carries the running max / sum /
weighted values across k blocks (the standard online-softmax recurrence).

Backward is recompute-based reference math under `jax.custom_vjp`; the
training path in `ray_tpu.ops.attention` uses the fused-backward kernel
for full train steps, this kernel owns the inference/prefill path.

No reference-counterpart: hellofinch/ray delegates all device math to
torch (SURVEY.md §2.4).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ray_tpu.ops.pallas._util import cdiv, interpret_mode

_NEG_INF = -1e30


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref, *,
                sm_scale: float, causal: bool, block_q: int, block_k: int,
                sq: int, sk: int):
    i_q = pl.program_id(1)
    i_k = pl.program_id(2)
    n_k = pl.num_programs(2)

    @pl.when(i_k == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    # Causal: row r attends keys <= r + (sk - sq) (diagonal offset aligns
    # the query window to the END of the key axis — the KV-cache decode
    # convention, matching _reference's tril(k=sk-sq)). Skip k blocks
    # entirely above the band.
    offset = sk - sq
    should_compute = True
    if causal:
        should_compute = (
            i_k * block_k <= i_q * block_q + block_q - 1 + offset)

    @pl.when(should_compute)
    def _compute():
        q = q_ref[0].astype(jnp.float32)  # [bq, d]
        k = k_ref[0].astype(jnp.float32)  # [bk, d]
        v = v_ref[0].astype(jnp.float32)  # [bk, d]
        # zero v's padded tail rows: their p weights are 0, but 0*garbage
        # (NaN in interpret mode) would still poison the p@v accumulate
        v_rows = jax.lax.broadcasted_iota(jnp.int32, v.shape, 0) + i_k * block_k
        v = jnp.where(v_rows < sk, v, 0.0)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale  # [bq, bk]
        cols = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1) + i_k * block_k
        if causal:
            rows = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0) + i_q * block_q
            s = jnp.where(cols <= rows + offset, s, _NEG_INF)
        # mask the padded key tail of the last block (sk % block_k != 0)
        s = jnp.where(cols < sk, s, _NEG_INF)

        m_prev = m_ref[:]                       # [bq, 1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                  # [bq, bk]
        alpha = jnp.exp(m_prev - m_new)         # rescale old state
        l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jnp.dot(
            p, v, preferred_element_type=jnp.float32)
        m_ref[:] = m_new

    @pl.when(i_k == n_k - 1)
    def _finalize():
        # Fully-masked rows (can't happen for causal self-attn) guard: l>=1e-30.
        l = jnp.maximum(l_ref[:], 1e-30)
        o_ref[0] = (acc_ref[:] / l).astype(o_ref.dtype)


def _flash_fwd(q, k, v, sm_scale, causal, block_q, block_k):
    bh, sq, d = q.shape
    sk = k.shape[1]
    bq = min(block_q, sq)
    bk = min(block_k, sk)
    grid = (bh, cdiv(sq, bq), cdiv(sk, bk))
    return pl.pallas_call(
        functools.partial(_fwd_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=bq, block_k=bk, sq=sq, sk=sk),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
        ],
        interpret=interpret_mode(),
    )(q, k, v)


def _reference(q, k, v, sm_scale, causal):
    logits = jnp.einsum("bqd,bkd->bqk", q, k).astype(jnp.float32) * sm_scale
    if causal:
        sq, sk = q.shape[1], k.shape[1]
        mask = jnp.tril(jnp.ones((sq, sk), dtype=bool), k=sk - sq)
        logits = jnp.where(mask, logits, _NEG_INF)
    p = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bqk,bkd->bqd", p, v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention_pallas(q: jax.Array, k: jax.Array, v: jax.Array,
                           sm_scale: float | None = None, causal: bool = True,
                           block_q: int = 256, block_k: int = 256) -> jax.Array:
    """Flash attention over [batch*heads, seq, head_dim] tensors."""
    scale = sm_scale if sm_scale is not None else 1.0 / (q.shape[-1] ** 0.5)
    return _flash_fwd(q, k, v, scale, causal, block_q, block_k)


def _vjp_fwd(q, k, v, sm_scale, causal, block_q, block_k):
    out = flash_attention_pallas(q, k, v, sm_scale, causal, block_q, block_k)
    return out, (q, k, v)


def _vjp_bwd(sm_scale, causal, block_q, block_k, res, g):
    q, k, v = res
    scale = sm_scale if sm_scale is not None else 1.0 / (q.shape[-1] ** 0.5)
    _, vjp = jax.vjp(lambda a, b, c: _reference(a, b, c, scale, causal), q, k, v)
    return vjp(g)


flash_attention_pallas.defvjp(_vjp_fwd, _vjp_bwd)
