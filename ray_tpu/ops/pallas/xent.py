"""Blockwise softmax cross-entropy Pallas kernel.

For a 32k–128k vocab the naive path materializes an fp32 softmax the size
of the logits — pure HBM traffic. This kernel streams vocab blocks through
VMEM keeping only running (max, sumexp, correct-logit) per row, and the
backward emits `softmax - onehot` blockwise from the saved logsumexp, so
no softmax tensor is ever stored.

No reference-counterpart (hellofinch/ray ships no kernels, SURVEY.md §2.4).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ray_tpu.ops.pallas._util import cdiv, interpret_mode

_NEG_INF = -1e30
_BLOCK_ROWS = 256
_BLOCK_V = 2048


def _fwd_kernel(x_ref, label_ref, loss_ref, lse_ref, m_ref, l_ref, c_ref, *,
                block_v: int, vocab: int):
    j = pl.program_id(1)
    n_v = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        c_ref[:] = jnp.zeros_like(c_ref)

    labels = label_ref[:]             # [br, 1] int32
    cols = jax.lax.broadcasted_iota(
        jnp.int32, (x_ref.shape[0], x_ref.shape[1]), 1) + j * block_v
    # mask the padded vocab tail of the last block (vocab % block_v != 0)
    x = jnp.where(cols < vocab, x_ref[:].astype(jnp.float32), _NEG_INF)

    m_prev = m_ref[:]
    m_new = jnp.maximum(m_prev, jnp.max(x, axis=-1, keepdims=True))
    l_ref[:] = l_ref[:] * jnp.exp(m_prev - m_new) + jnp.sum(
        jnp.exp(x - m_new), axis=-1, keepdims=True)
    m_ref[:] = m_new
    c_ref[:] += jnp.sum(jnp.where(cols == labels, x, 0.0), axis=-1, keepdims=True)

    @pl.when(j == n_v - 1)
    def _finalize():
        lse = m_ref[:] + jnp.log(jnp.maximum(l_ref[:], 1e-30))
        lse_ref[:] = lse
        loss_ref[:] = lse - c_ref[:]


def _bwd_kernel(x_ref, label_ref, lse_ref, g_ref, dx_ref, *, block_v: int,
                vocab: int):
    j = pl.program_id(1)
    x = x_ref[:].astype(jnp.float32)
    cols = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1) + j * block_v
    p = jnp.where(cols < vocab, jnp.exp(x - lse_ref[:]), 0.0)
    onehot = (cols == label_ref[:]).astype(jnp.float32)
    dx_ref[:] = ((p - onehot) * g_ref[:]).astype(dx_ref.dtype)


def _run_fwd(logits, labels2d):
    rows, v = logits.shape
    br = min(_BLOCK_ROWS, rows)
    bv = min(_BLOCK_V, v)
    grid = (cdiv(rows, br), cdiv(v, bv))
    loss, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, block_v=bv, vocab=v),
        grid=grid,
        in_specs=[
            pl.BlockSpec((br, bv), lambda i, j: (i, j), memory_space=pltpu.VMEM),
            pl.BlockSpec((br, 1), lambda i, j: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((br, 1), lambda i, j: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((br, 1), lambda i, j: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rows, 1), jnp.float32),
            jax.ShapeDtypeStruct((rows, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((br, 1), jnp.float32),
            pltpu.VMEM((br, 1), jnp.float32),
            pltpu.VMEM((br, 1), jnp.float32),
        ],
        interpret=interpret_mode(),
    )(logits, labels2d)
    return loss, lse


@jax.custom_vjp
def softmax_cross_entropy_pallas(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Per-example cross-entropy. logits [N, V] (any dtype), labels [N] int.

    Returns fp32 loss [N]. Gradient flows to logits only.
    """
    loss, _ = _run_fwd(logits, labels.astype(jnp.int32).reshape(-1, 1))
    return loss[:, 0]


def _vjp_fwd(logits, labels):
    labels2d = labels.astype(jnp.int32).reshape(-1, 1)
    loss, lse = _run_fwd(logits, labels2d)
    return loss[:, 0], (logits, labels2d, lse)


def _vjp_bwd(res, g):
    logits, labels2d, lse = res
    rows, v = logits.shape
    br = min(_BLOCK_ROWS, rows)
    bv = min(_BLOCK_V, v)
    grid = (cdiv(rows, br), cdiv(v, bv))
    dx = pl.pallas_call(
        functools.partial(_bwd_kernel, block_v=bv, vocab=v),
        grid=grid,
        in_specs=[
            pl.BlockSpec((br, bv), lambda i, j: (i, j), memory_space=pltpu.VMEM),
            pl.BlockSpec((br, 1), lambda i, j: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((br, 1), lambda i, j: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((br, 1), lambda i, j: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((br, bv), lambda i, j: (i, j), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((rows, v), logits.dtype),
        interpret=interpret_mode(),
    )(logits, labels2d, lse, g.astype(jnp.float32).reshape(-1, 1))
    return dx, None


softmax_cross_entropy_pallas.defvjp(_vjp_fwd, _vjp_bwd)
