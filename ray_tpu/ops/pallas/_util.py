"""Shared helpers for the Pallas kernels."""

from __future__ import annotations

import jax


def on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def interpret_mode() -> bool:
    """Kernels compile with Mosaic on TPU, interpret elsewhere (CI CPU mesh)."""
    return not on_tpu()


def cdiv(a: int, b: int) -> int:
    return -(-a // b)


def round_up(a: int, b: int) -> int:
    return cdiv(a, b) * b
