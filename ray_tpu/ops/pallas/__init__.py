"""Hand-written Pallas TPU kernels for the hot ops.

The reference (hellofinch/ray) ships no kernels of its own — GPU math is
delegated to torch/NCCL (SURVEY.md §2.4). On TPU the equivalent hot-path
ownership is these Mosaic kernels: fused RMSNorm, flash attention with
online softmax, blockwise cross-entropy, and int8 quantization.

Every kernel runs under `interpret=True` off-TPU so the full test suite
exercises kernel math on the CI CPU mesh.
"""

from ray_tpu.ops.pallas.rmsnorm import rms_norm_pallas
from ray_tpu.ops.pallas.flash_attention import flash_attention_pallas
from ray_tpu.ops.pallas.xent import softmax_cross_entropy_pallas
from ray_tpu.ops.pallas.quant import quantize_int8, dequantize_int8

__all__ = [
    "rms_norm_pallas",
    "flash_attention_pallas",
    "softmax_cross_entropy_pallas",
    "quantize_int8",
    "dequantize_int8",
]
