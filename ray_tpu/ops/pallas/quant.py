"""Int8 row-wise quantization kernels (weights / KV-cache compression).

Per-row absmax scales; round-to-nearest. Used by the inference engine to
halve KV-cache HBM footprint and by checkpoint compression.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ray_tpu.ops.pallas._util import cdiv, interpret_mode

_BLOCK_ROWS = 256


def _quant_kernel(x_ref, v_ref, s_ref):
    x = x_ref[:].astype(jnp.float32)
    absmax = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
    scale = jnp.maximum(absmax, 1e-8) / 127.0
    s_ref[:] = scale
    v_ref[:] = jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)


def _dequant_kernel(v_ref, s_ref, o_ref):
    o_ref[:] = (v_ref[:].astype(jnp.float32) * s_ref[:]).astype(o_ref.dtype)


def quantize_int8(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """[..., d] → (int8 values [..., d], fp32 scales [..., 1])."""
    shape = x.shape
    x2d = x.reshape(-1, shape[-1])
    rows, d = x2d.shape
    br = min(_BLOCK_ROWS, rows)
    values, scales = pl.pallas_call(
        _quant_kernel,
        grid=(cdiv(rows, br),),
        in_specs=[pl.BlockSpec((br, d), lambda i: (i, 0), memory_space=pltpu.VMEM)],
        out_specs=[
            pl.BlockSpec((br, d), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((br, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rows, d), jnp.int8),
            jax.ShapeDtypeStruct((rows, 1), jnp.float32),
        ],
        interpret=interpret_mode(),
    )(x2d)
    return values.reshape(shape), scales.reshape(*shape[:-1], 1)


def dequantize_int8(values: jax.Array, scales: jax.Array,
                    dtype=jnp.bfloat16) -> jax.Array:
    shape = values.shape
    v2d = values.reshape(-1, shape[-1])
    s2d = scales.reshape(-1, 1)
    rows, d = v2d.shape
    br = min(_BLOCK_ROWS, rows)
    out = pl.pallas_call(
        _dequant_kernel,
        grid=(cdiv(rows, br),),
        in_specs=[
            pl.BlockSpec((br, d), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((br, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((br, d), lambda i: (i, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((rows, d), dtype),
        interpret=interpret_mode(),
    )(v2d, s2d)
    return out.reshape(shape)
