"""Mixture-of-Experts block with expert parallelism.

Absent from the reference entirely (SURVEY §2.4: EP/MoE = none in-tree) —
green-field, TPU-first design: GShard-style top-2 gating with static expert
capacity, dispatch/combine einsums over stacked expert weights [E, ...].
When the "expert" logical axis is sharded over a mesh axis, XLA compiles
the dispatch/combine einsums into all-to-alls over ICI — no manual
collectives. Static capacity keeps every shape compile-time constant
(XLA-friendly; overflowing tokens are dropped, the standard trade).
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp


def top2_gating(router_logits: jax.Array, capacity: int):
    """Build dispatch/combine tensors.

    router_logits: [T, E]. Returns (dispatch [T,E,C] bool-ish float,
    combine [T,E,C] float, aux_loss scalar).
    """
    T, E = router_logits.shape
    probs = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)

    # top-1 and top-2 expert per token
    idx1 = jnp.argmax(probs, axis=-1)                       # [T]
    p1 = jnp.take_along_axis(probs, idx1[:, None], axis=-1)[:, 0]
    masked = probs * (1.0 - jax.nn.one_hot(idx1, E))
    idx2 = jnp.argmax(masked, axis=-1)
    p2 = jnp.take_along_axis(masked, idx2[:, None], axis=-1)[:, 0]

    # renormalize the pair
    denom = jnp.maximum(p1 + p2, 1e-9)
    w1, w2 = p1 / denom, p2 / denom

    # position of each token within its expert's capacity (running count)
    mask1 = jax.nn.one_hot(idx1, E)                         # [T, E]
    pos1 = (jnp.cumsum(mask1, axis=0) - 1.0) * mask1        # [T, E]
    mask2 = jax.nn.one_hot(idx2, E)
    pos2 = (jnp.cumsum(mask2, axis=0) + jnp.sum(mask1, axis=0, keepdims=True)
            - 1.0) * mask2

    keep1 = (pos1 < capacity) * mask1
    keep2 = (pos2 < capacity) * mask2

    def scatter(keep, pos, w):
        # [T,E] keep/pos + [T] weight -> [T,E,C]
        pos_idx = jnp.clip(pos, 0, capacity - 1).astype(jnp.int32)
        onehot_c = jax.nn.one_hot(pos_idx, capacity) * keep[..., None]
        return onehot_c * w[:, None, None]

    combine = scatter(keep1, pos1, w1) + scatter(keep2, pos2, w2)
    dispatch = (combine > 0).astype(router_logits.dtype)

    # load-balancing auxiliary loss (Switch/GShard)
    density = jnp.mean(mask1, axis=0)                       # fraction routed
    density_proxy = jnp.mean(probs, axis=0)
    aux_loss = jnp.sum(density * density_proxy) * (E * E) / E
    return dispatch.astype(jnp.float32), combine.astype(jnp.float32), aux_loss


def moe_ffn(x: jax.Array, router_w: jax.Array, w_gate: jax.Array,
            w_up: jax.Array, w_down: jax.Array,
            capacity_factor: float = 1.25) -> Tuple[jax.Array, jax.Array]:
    """MoE SwiGLU FFN. x: [B, S, d]; router_w: [d, E];
    expert weights stacked [E, d, ff] / [E, ff, d].

    Returns (out [B,S,d], aux_loss).
    """
    B, S, d = x.shape
    E = router_w.shape[-1]
    T = B * S
    capacity = max(1, int(capacity_factor * T / E))
    xt = x.reshape(T, d)

    router_logits = (xt.astype(jnp.float32) @ router_w.astype(jnp.float32))
    dispatch, combine, aux = top2_gating(router_logits, capacity)

    # dispatch tokens to experts: [E, C, d]
    expert_in = jnp.einsum("tec,td->ecd", dispatch.astype(x.dtype), xt)
    # per-expert SwiGLU over stacked weights (sharded over the expert axis)
    gate = jnp.einsum("ecd,edf->ecf", expert_in, w_gate)
    up = jnp.einsum("ecd,edf->ecf", expert_in, w_up)
    act = jax.nn.silu(gate) * up
    expert_out = jnp.einsum("ecf,efd->ecd", act, w_down)
    # combine back: [T, d]
    out = jnp.einsum("tec,ecd->td", combine.astype(x.dtype), expert_out)
    return out.reshape(B, S, d), aux
