"""Attention: in-repo Pallas flash kernel on TPU, reference einsum elsewhere.

The TPU path uses this repo's Pallas flash-attention kernels
(`ray_tpu.ops.pallas.flash_attention`) for BOTH forward and backward —
tiled onto the MXU with online softmax and a fused FlashAttention-2
recompute backward, O(seq) memory in each direction. The reference path is
a plain einsum attention used on CPU (tests / virtual meshes) and as the
ground truth the kernels are checked against.

GQA (fewer KV heads than Q heads) is handled by repeating KV heads before
the kernel; XLA turns the repeat into a broadcast so no HBM copy occurs.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def causal_attention_reference(q, k, v, sm_scale: Optional[float] = None,
                               causal: bool = True) -> jax.Array:
    """Ground-truth attention. [batch, heads, seq, head_dim] layout."""
    *_, sq, d = q.shape
    sk = k.shape[-2]
    scale = sm_scale if sm_scale is not None else 1.0 / (d ** 0.5)
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        mask = jnp.tril(jnp.ones((sq, sk), dtype=bool), k=sk - sq)
        logits = jnp.where(mask, logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


def _repeat_kv(k: jax.Array, n_rep: int) -> jax.Array:
    if n_rep == 1:
        return k
    b, h, s, d = k.shape
    return jnp.broadcast_to(k[:, :, None], (b, h, n_rep, s, d)).reshape(b, h * n_rep, s, d)


@functools.partial(jax.jit, static_argnames=("causal", "sm_scale"))
def attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
              causal: bool = True, sm_scale: Optional[float] = None) -> jax.Array:
    """Multi-head attention, [batch, heads, seq, head_dim]; supports GQA.

    Dispatches to the TPU pallas flash kernel when running on TPU and the
    shapes satisfy its tiling constraints; otherwise falls back to the
    reference einsum (which XLA still fuses reasonably on TPU).
    """
    n_rep = q.shape[1] // k.shape[1]
    k = _repeat_kv(k, n_rep)
    v = _repeat_kv(v, n_rep)
    scale = sm_scale if sm_scale is not None else 1.0 / (q.shape[-1] ** 0.5)
    if _on_tpu() and q.shape[-1] >= 128 and q.shape[-2] >= 128:
        from ray_tpu.ops.pallas.flash_attention import flash_attention_pallas

        b, h, sq, d = q.shape
        sk = k.shape[-2]
        # block_k 1024 (vs 512) is ~25% faster fwd+bwd on v5e at seq 2048:
        # fewer grid steps on the sequential k axis amortize accumulator
        # spills; block_q stays 512 to bound VMEM for the dkv kernel.
        out = flash_attention_pallas(
            q.reshape(b * h, sq, d), k.reshape(b * h, sk, d),
            v.reshape(b * h, sk, d), scale, causal,
            min(512, sq), min(1024, sk))
        return out.reshape(b, h, sq, d)
    return causal_attention_reference(q, k, v, sm_scale=scale, causal=causal)
