"""Attention: in-repo Pallas flash kernel on TPU, reference einsum elsewhere.

The TPU path uses this repo's Pallas flash-attention kernels
(`ray_tpu.ops.pallas.flash_attention`) for BOTH forward and backward —
tiled onto the MXU with online softmax and a fused FlashAttention-2
recompute backward, O(seq) memory in each direction. The reference path is
a plain einsum attention used on CPU (tests / virtual meshes) and as the
ground truth the kernels are checked against.

GQA (fewer KV heads than Q heads) is handled by repeating KV heads before
the kernel; XLA turns the repeat into a broadcast so no HBM copy occurs.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def causal_attention_reference(q, k, v, sm_scale: Optional[float] = None,
                               causal: bool = True) -> jax.Array:
    """Ground-truth attention. [batch, heads, seq, head_dim] layout."""
    *_, sq, d = q.shape
    sk = k.shape[-2]
    scale = sm_scale if sm_scale is not None else 1.0 / (d ** 0.5)
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        mask = jnp.tril(jnp.ones((sq, sk), dtype=bool), k=sk - sq)
        logits = jnp.where(mask, logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


def _repeat_kv(k: jax.Array, n_rep: int) -> jax.Array:
    if n_rep == 1:
        return k
    b, h, s, d = k.shape
    return jnp.broadcast_to(k[:, :, None], (b, h, n_rep, s, d)).reshape(b, h * n_rep, s, d)


@functools.partial(jax.jit, static_argnames=("causal", "sm_scale"))
def attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
              causal: bool = True, sm_scale: Optional[float] = None) -> jax.Array:
    """Multi-head attention, [batch, heads, seq, head_dim]; supports GQA.

    Dispatches to the TPU pallas flash kernel when running on TPU and the
    shapes satisfy its tiling constraints; otherwise falls back to the
    reference einsum (which XLA still fuses reasonably on TPU).
    """
    n_rep = q.shape[1] // k.shape[1]
    k = _repeat_kv(k, n_rep)
    v = _repeat_kv(v, n_rep)
    scale = sm_scale if sm_scale is not None else 1.0 / (q.shape[-1] ** 0.5)
    if _on_tpu() and q.shape[-1] >= 128 and q.shape[-2] >= 128:
        from ray_tpu.ops.pallas.flash_attention import flash_attention_pallas

        b, h, sq, d = q.shape
        sk = k.shape[-2]
        # Measured on v5e at seq 2048 / head_dim 128 (see flash kernel
        # docstring): fwd peaks at (1024, 1024) blocks — 95% of bf16 peak vs
        # 43% at (512, 1024); the bwd pair peaks at (1024, 512) — the dkv
        # kernel carries two k-block f32 accumulators, so a smaller k block
        # keeps its VMEM footprint down while a big q block amortizes the
        # sequential-axis revisits.
        out = flash_attention_pallas(
            q.reshape(b * h, sq, d), k.reshape(b * h, sk, d),
            v.reshape(b * h, sk, d), scale, causal,
            min(1024, sq), min(1024, sk),
            min(1024, sq), min(512, sk))
        return out.reshape(b, h, sq, d)
    return causal_attention_reference(q, k, v, sm_scale=scale, causal=causal)


@functools.partial(jax.jit, static_argnames=("n_heads", "n_kv_heads",
                                             "causal", "sm_scale"))
def attention_packed(q: jax.Array, k: jax.Array, v: jax.Array, *,
                     n_heads: int, n_kv_heads: int, causal: bool = True,
                     sm_scale: Optional[float] = None) -> jax.Array:
    """Attention over packed [batch, seq, heads*head_dim] tensors — the
    layout the q/k/v projections produce and the output projection consumes.

    On TPU this runs the packed flash kernel (no [b,s,h,d]<->[b,h,s,d]
    transposes, GQA k/v never expanded); elsewhere it falls back to the
    reference einsum via free reshapes."""
    b, sq, hd = q.shape
    d = hd // n_heads
    sk = k.shape[1]
    scale = sm_scale if sm_scale is not None else 1.0 / (d ** 0.5)
    if _on_tpu() and d >= 128 and sq >= 128:
        from ray_tpu.ops.pallas.flash_attention import flash_attention_packed

        return flash_attention_packed(q, k, v, n_heads, n_kv_heads, scale,
                                      causal, min(1024, sq), min(1024, sk),
                                      min(1024, sq), min(512, sk))
    q4 = q.reshape(b, sq, n_heads, d).transpose(0, 2, 1, 3)
    k4 = k.reshape(b, sk, n_kv_heads, d).transpose(0, 2, 1, 3)
    v4 = v.reshape(b, sk, n_kv_heads, d).transpose(0, 2, 1, 3)
    n_rep = n_heads // n_kv_heads
    out = causal_attention_reference(q4, _repeat_kv(k4, n_rep),
                                     _repeat_kv(v4, n_rep),
                                     sm_scale=scale, causal=causal)
    return out.transpose(0, 2, 1, 3).reshape(b, sq, hd)
