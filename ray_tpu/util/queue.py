"""Distributed FIFO queue (actor-backed).

Mirrors `ray.util.queue.Queue` (reference `python/ray/util/queue.py`).
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, List, Optional

import ray_tpu


@ray_tpu.remote
class _QueueActor:
    def __init__(self, maxsize: int = 0):
        self._maxsize = maxsize
        self._q = collections.deque()

    def put(self, item) -> bool:
        if self._maxsize and len(self._q) >= self._maxsize:
            return False
        self._q.append(item)
        return True

    def get_batch(self, max_items: int = 100) -> List[Any]:
        out = []
        while self._q and len(out) < max_items:
            out.append(self._q.popleft())
        return out

    def qsize(self) -> int:
        return len(self._q)


class Queue:
    """Client facade; pass the Queue object (it pickles by actor handle)."""

    def __init__(self, maxsize: int = 0, actor_options: Optional[dict] = None):
        opts = dict(actor_options or {})
        opts.setdefault("num_cpus", 0)
        self._actor = _QueueActor.options(**opts).remote(maxsize)

    def put(self, item, block: bool = True, timeout: Optional[float] = None) -> None:
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            if ray_tpu.get(self._actor.put.remote(item)):
                return
            if not block or (deadline and time.monotonic() > deadline):
                raise TimeoutError("queue full")
            time.sleep(0.05)

    def get_batch(self, max_items: int = 100) -> List[Any]:
        return ray_tpu.get(self._actor.get_batch.remote(max_items))

    def get(self, block: bool = True, timeout: Optional[float] = None):
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            batch = self.get_batch(1)
            if batch:
                return batch[0]
            if not block or (deadline and time.monotonic() > deadline):
                raise TimeoutError("queue empty")
            time.sleep(0.02)

    def qsize(self) -> int:
        return ray_tpu.get(self._actor.qsize.remote())

    def __reduce__(self):
        q = object.__new__(Queue)
        return (_rebuild_queue, (self._actor,))


def _rebuild_queue(actor):
    q = object.__new__(Queue)
    q._actor = actor
    return q
