"""Opt-in OpenTelemetry bridge for the built-in tracing spans.

Reference parity: python/ray/util/tracing/tracing_helper.py:35-89 — the
reference wraps task submission/execution in OTel spans when the user
passes `_tracing_startup_hook` to ray.init. Here the built-in chrome-trace
spans (util/tracing.py) are the single instrumentation layer; calling
`enable_otel_tracing()` mirrors every completed span into an OTel tracer,
so any configured exporter (OTLP, console, in-memory for tests) sees task
submission/execution spans without a second instrumentation pass.
"""

from __future__ import annotations

from typing import Any, Optional

from ray_tpu.util import tracing

_state = {"hook": None}


def enable_otel_tracing(tracer_provider: Optional[Any] = None) -> None:
    """Mirror framework spans into OpenTelemetry. Pass a TracerProvider to
    control exporting (defaults to the global provider)."""
    from opentelemetry import trace as ot_trace

    if _state["hook"] is not None:
        return
    provider = tracer_provider or ot_trace.get_tracer_provider()
    tracer = provider.get_tracer("ray_tpu")

    def hook(event: dict) -> None:
        # translate the chrome-trace X event (perf_counter us) into a
        # real-time-anchored OTel span
        import time

        end_ns = time.time_ns()
        start_ns = end_ns - int(event["dur"] * 1000)
        span = tracer.start_span(event["name"], start_time=start_ns)
        span.set_attribute("category", event.get("cat", ""))
        for k, v in (event.get("args") or {}).items():
            if isinstance(v, (str, int, float, bool)):
                span.set_attribute(k, v)
        span.end(end_time=end_ns)

    _state["hook"] = hook
    tracing.add_span_hook(hook)


def disable_otel_tracing() -> None:
    if _state["hook"] is not None:
        tracing.remove_span_hook(_state["hook"])
        _state["hook"] = None
