"""multiprocessing.Pool API over the task runtime.

Mirrors the reference's `ray.util.multiprocessing.Pool`
(`python/ray/util/multiprocessing/pool.py`): the stdlib Pool surface —
apply/apply_async/map/map_async/imap/imap_unordered/starmap — where each
work item runs as a cluster task instead of a forked local process.
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Iterable, List, Optional, Sequence

import ray_tpu


class AsyncResult:
    def __init__(self, refs: List[Any], single: bool):
        self._refs = refs
        self._single = single

    def get(self, timeout: Optional[float] = None):
        results = ray_tpu.get(self._refs, timeout=timeout)
        return results[0] if self._single else results

    def wait(self, timeout: Optional[float] = None) -> None:
        ray_tpu.wait(self._refs, num_returns=len(self._refs), timeout=timeout)

    def ready(self) -> bool:
        done, _ = ray_tpu.wait(self._refs, num_returns=len(self._refs),
                               timeout=0)
        return len(done) == len(self._refs)

    def successful(self) -> bool:
        if not self.ready():
            raise ValueError("result is not ready")  # stdlib Pool contract
        try:
            ray_tpu.get(self._refs, timeout=0)
            return True
        except Exception:
            return False


@ray_tpu.remote
def _run_chunk(fn: Callable, chunk: List[Any], star: bool) -> List[Any]:
    if star:
        return [fn(*args) for args in chunk]
    return [fn(x) for x in chunk]


class Pool:
    """Task-backed process pool. All chunks are submitted eagerly — actual
    execution concurrency is bounded by cluster CPU resources (each chunk
    is a 1-CPU task queued by the scheduler), not by `processes`, which
    only feeds the default-chunksize heuristic. `chunksize` groups items
    per task like the stdlib."""

    def __init__(self, processes: Optional[int] = None):
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        self._processes = processes or int(
            ray_tpu.cluster_resources().get("CPU", 4))
        self._closed = False

    # ---------------------------------------------------------------- sync
    def apply(self, fn: Callable, args: Sequence = (), kwds: Optional[dict] = None):
        return self.apply_async(fn, args, kwds).get()

    def map(self, fn: Callable, iterable: Iterable,
            chunksize: Optional[int] = None) -> List[Any]:
        return self.map_async(fn, iterable, chunksize).get()

    def starmap(self, fn: Callable, iterable: Iterable[Sequence],
                chunksize: Optional[int] = None) -> List[Any]:
        refs = self._submit_chunks(fn, list(iterable), chunksize, star=True)
        return list(itertools.chain.from_iterable(ray_tpu.get(refs)))

    # --------------------------------------------------------------- async
    def apply_async(self, fn: Callable, args: Sequence = (),
                    kwds: Optional[dict] = None) -> AsyncResult:
        self._check_open()
        kwds = kwds or {}

        @ray_tpu.remote
        def _apply(f, a, kw):
            return f(*a, **kw)

        return AsyncResult([_apply.remote(fn, list(args), kwds)], single=True)

    def map_async(self, fn: Callable, iterable: Iterable,
                  chunksize: Optional[int] = None) -> AsyncResult:
        refs = self._submit_chunks(fn, list(iterable), chunksize, star=False)
        return _ChunkedResult(refs)

    # ---------------------------------------------------------------- imap
    def imap(self, fn: Callable, iterable: Iterable,
             chunksize: Optional[int] = None):
        items = list(iterable)
        refs = self._submit_chunks(fn, items, chunksize, star=False)
        for ref in refs:
            yield from ray_tpu.get(ref)

    def imap_unordered(self, fn: Callable, iterable: Iterable,
                       chunksize: Optional[int] = None):
        items = list(iterable)
        refs = self._submit_chunks(fn, items, chunksize, star=False)
        remaining = list(refs)
        while remaining:
            done, remaining = ray_tpu.wait(remaining, num_returns=1)
            for ref in done:
                yield from ray_tpu.get(ref)

    # ------------------------------------------------------------ lifecycle
    def close(self) -> None:
        self._closed = True

    def terminate(self) -> None:
        self._closed = True

    def join(self) -> None:
        pass

    def __enter__(self) -> "Pool":
        return self

    def __exit__(self, *exc) -> None:
        self.terminate()

    # ------------------------------------------------------------ internals
    def _check_open(self) -> None:
        if self._closed:
            raise ValueError("Pool not running")

    def _submit_chunks(self, fn: Callable, items: List[Any],
                       chunksize: Optional[int], star: bool) -> List[Any]:
        self._check_open()
        if not items:
            return []
        if chunksize is None:
            chunksize = max(1, len(items) // (self._processes * 4) or 1)
        return [
            _run_chunk.remote(fn, items[i:i + chunksize], star)
            for i in range(0, len(items), chunksize)]


class _ChunkedResult(AsyncResult):
    def __init__(self, refs: List[Any]):
        super().__init__(refs, single=False)

    def get(self, timeout: Optional[float] = None):
        chunks = ray_tpu.get(self._refs, timeout=timeout)
        return list(itertools.chain.from_iterable(chunks))
