"""Version bridges over jax API drift.

The repo targets the modern top-level `jax.shard_map(f, mesh=..., in_specs=...,
out_specs=..., check_vma=..., axis_names=...)`.  Older jax (< 0.5) only ships
`jax.experimental.shard_map.shard_map`, with two renamed knobs:

  - ``check_vma``  -> ``check_rep`` (same meaning: verify per-axis
    replication/varying-mesh-axes annotations)
  - ``axis_names`` (the axes that ARE manual) -> ``auto`` (the axes that are
    NOT manual) — inverse sense, so we complement against the mesh's axes.

Call sites import :func:`shard_map` from here and always use the modern
keyword spelling; the shim forwards to whichever implementation exists.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Set

import jax


def shard_map(f: Callable, *, mesh: Any, in_specs: Any, out_specs: Any,
              check_vma: bool = True,
              axis_names: Optional[Set[str]] = None) -> Callable:
    """`jax.shard_map` when available, else the experimental equivalent."""
    if hasattr(jax, "shard_map"):
        kwargs = {}
        if axis_names is not None:
            kwargs["axis_names"] = set(axis_names)
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma,
                             **kwargs)
    from jax.experimental.shard_map import shard_map as _shard_map

    auto: frozenset = frozenset()
    if axis_names is not None:
        auto = frozenset(mesh.axis_names) - frozenset(axis_names)
    return _shard_map(f, mesh, in_specs=in_specs, out_specs=out_specs,
                      check_rep=check_vma, auto=auto)
