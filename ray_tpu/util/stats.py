"""Tiny shared statistics helpers (no numpy dependency on hot paths)."""

from __future__ import annotations

from typing import List, Optional


def percentile(sorted_vals: List[float], q: float) -> Optional[float]:
    """Nearest-rank percentile of an ALREADY-SORTED list; None when empty.
    Shared by the serve storm harness and the worker pool's fork-latency
    stats — one index formula, one rounding behavior."""
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1, int(q * (len(sorted_vals) - 1) + 0.5))
    return sorted_vals[idx]
