"""Trailing-edge debouncer for fire-and-forget control-plane notifies.

One shared implementation for the completion-path rate limits (raylet
resource reports, GCS resource broadcasts): `fn` runs at most once per
period, a call landing inside the quiet window arms ONE timer that fires
`fn` at the window's edge — so a burst coalesces but the final post-burst
state always goes out — and `force=True` bypasses the debounce entirely
(topology changes must never wait)."""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Optional

logger = logging.getLogger(__name__)


class Debouncer:
    def __init__(self, fn: Callable[[], None],
                 period_fn: Callable[[], float],
                 skip_deferred: Optional[Callable[[], bool]] = None):
        """`period_fn` is re-read per call so config changes apply live;
        `skip_deferred` (e.g. shutdown-flag check) drops a timer fire whose
        process is already exiting."""
        self._fn = fn
        self._period_fn = period_fn
        self._skip_deferred = skip_deferred
        self._lock = threading.Lock()
        self._last = 0.0
        self._pending = False

    def __call__(self, force: bool = False) -> None:
        now = time.monotonic()
        if force:
            with self._lock:
                self._last = now
            self._fn()
            return
        period = self._period_fn()
        with self._lock:
            if now - self._last < period:
                if not self._pending:
                    self._pending = True
                    t = threading.Timer(self._last + period - now, self._fire)
                    t.daemon = True
                    t.start()
                return
            self._last = now
        self._fn()

    def _fire(self) -> None:
        with self._lock:
            self._pending = False
            self._last = time.monotonic()
        if self._skip_deferred is not None and self._skip_deferred():
            return
        try:
            self._fn()
        except Exception:
            logger.debug("deferred debounced call failed", exc_info=True)
