"""User-facing metrics: Counter / Gauge / Histogram + Prometheus text export.

Mirrors `ray.util.metrics` (reference `python/ray/util/metrics.py`) and the
Prometheus export path (reference metrics_agent -> scrape endpoint); here a
process-local registry renders the standard text exposition format, served
by the dashboard (`ray_tpu.dashboard`).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

# RLock: get_or_create holds it across lookup+construction (the Metric ctor
# re-enters it to self-register), so two threads can never race to register
# the same name and split increments across duplicate instances.
_registry_lock = threading.RLock()
_registry: List["Metric"] = []


class Metric:
    def __init__(self, name: str, description: str = "",
                 tag_keys: Sequence[str] = ()):
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys)
        self._default_tags: Dict[str, str] = {}
        self._values: Dict[Tuple, float] = {}
        # per-source series merged in from other processes (see
        # merge_snapshot); combined with local values at export time
        self._remote: Dict[str, Dict[str, Any]] = {}
        self._lock = threading.Lock()
        with _registry_lock:
            _registry.append(self)

    def _combined_values(self) -> Dict[Tuple, float]:
        """Local + remote series: counters sum per tag key, gauges take the
        remote value when present (the remote process owns that series)."""
        out = dict(self._values)
        additive = getattr(self, "kind", "") == "counter"
        for entry in self._remote.values():
            for k, v in entry.get("values", {}).items():
                if additive:
                    out[k] = out.get(k, 0.0) + v
                else:
                    out[k] = v
        return out

    def set_default_tags(self, tags: Dict[str, str]):
        self._default_tags = dict(tags)
        return self

    def _key(self, tags: Optional[Dict[str, str]]) -> Tuple:
        merged = dict(self._default_tags)
        if tags:
            merged.update(tags)
        return tuple(sorted(merged.items()))

    def _fmt_labels(self, key: Tuple) -> str:
        if not key:
            return ""
        inner = ",".join(f'{k}="{v}"' for k, v in key)
        return "{" + inner + "}"


class Counter(Metric):
    kind = "counter"

    def inc(self, value: float = 1.0, tags: Optional[Dict[str, str]] = None):
        with self._lock:
            k = self._key(tags)
            self._values[k] = self._values.get(k, 0.0) + value


class Gauge(Metric):
    kind = "gauge"

    def set(self, value: float, tags: Optional[Dict[str, str]] = None):
        with self._lock:
            self._values[self._key(tags)] = value


class Histogram(Metric):
    kind = "histogram"

    def __init__(self, name: str, description: str = "",
                 boundaries: Sequence[float] = (0.01, 0.1, 1, 10, 100),
                 tag_keys: Sequence[str] = ()):
        super().__init__(name, description, tag_keys)
        self.boundaries = sorted(boundaries)
        self._counts: Dict[Tuple, List[int]] = {}
        self._sums: Dict[Tuple, float] = {}
        self._totals: Dict[Tuple, int] = {}

    def observe(self, value: float, tags: Optional[Dict[str, str]] = None):
        with self._lock:
            k = self._key(tags)
            counts = self._counts.setdefault(k, [0] * (len(self.boundaries) + 1))
            for i, b in enumerate(self.boundaries):
                if value <= b:
                    counts[i] += 1
                    break
            else:
                counts[-1] += 1
            self._sums[k] = self._sums.get(k, 0.0) + value
            self._totals[k] = self._totals.get(k, 0) + 1


def export_prometheus() -> str:
    """Render all registered metrics in Prometheus text format."""
    lines: List[str] = []
    with _registry_lock:
        metrics = list(_registry)
    for m in metrics:
        lines.append(f"# HELP {m.name} {m.description}")
        lines.append(f"# TYPE {m.name} {m.kind}")
        if isinstance(m, Histogram):
            with m._lock:
                # combine local + merged remote series additively per tag key
                counts_by_k = {k: list(v) for k, v in m._counts.items()}
                sums = dict(m._sums)
                totals = dict(m._totals)
                for entry in m._remote.values():
                    for k, v in entry.get("counts", {}).items():
                        cur = counts_by_k.setdefault(
                            k, [0] * (len(m.boundaries) + 1))
                        for i, c in enumerate(v):
                            cur[i] += c
                    for k, v in entry.get("sums", {}).items():
                        sums[k] = sums.get(k, 0.0) + v
                    for k, v in entry.get("totals", {}).items():
                        totals[k] = totals.get(k, 0) + v
            for k, counts in counts_by_k.items():
                cum = 0
                for i, b in enumerate(m.boundaries):
                    cum += counts[i]
                    labels = dict(k)
                    labels["le"] = str(b)
                    inner = ",".join(f'{kk}="{vv}"' for kk, vv in sorted(labels.items()))
                    lines.append(f"{m.name}_bucket{{{inner}}} {cum}")
                cum += counts[-1]
                labels = dict(k)
                labels["le"] = "+Inf"
                inner = ",".join(f'{kk}="{vv}"' for kk, vv in sorted(labels.items()))
                lines.append(f"{m.name}_bucket{{{inner}}} {cum}")
                lines.append(f"{m.name}_sum{m._fmt_labels(k)} {sums.get(k, 0.0)}")
                lines.append(f"{m.name}_count{m._fmt_labels(k)} {totals.get(k, 0)}")
        else:
            with m._lock:
                combined = m._combined_values()
            for k, v in combined.items():
                lines.append(f"{m.name}{m._fmt_labels(k)} {v}")
    return "\n".join(lines) + "\n"


def get_or_create(kind: str, name: str, description: str = "",
                  **kwargs) -> Metric:
    """Get the registered metric `name`, creating it on first use — the
    one lazy-singleton helper for framework-internal metrics."""
    cls = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}[kind]
    with _registry_lock:
        for m in _registry:
            if m.name == name:
                return m
        return cls(name, description, **kwargs)


def snapshot(prefix: str = "") -> Dict[str, Dict[str, Any]]:
    """Serializable dump of this process's metrics (optionally filtered by
    name prefix) for shipping to another process's registry."""
    with _registry_lock:
        metrics = [m for m in _registry if m.name.startswith(prefix)]
    out: Dict[str, Dict[str, Any]] = {}
    for m in metrics:
        with m._lock:
            entry: Dict[str, Any] = {
                "kind": m.kind, "description": m.description,
                "tag_keys": m.tag_keys, "values": dict(m._values),
            }
            if isinstance(m, Histogram):
                entry["boundaries"] = list(m.boundaries)
                entry["counts"] = {k: list(v) for k, v in m._counts.items()}
                entry["sums"] = dict(m._sums)
                entry["totals"] = dict(m._totals)
            out[m.name] = entry
    return out


def merge_snapshot(snap: Dict[str, Dict[str, Any]], source: str = "remote") -> None:
    """Install another process's snapshot into this registry under `source`.

    Remote series are kept SEPARATE from local values and re-installed
    wholesale on every merge (idempotent per scrape); export combines them
    — additively for counters/histograms, remote-wins for gauges. This way
    mixed traffic (e.g. driver-side handle calls + HTTP-proxy requests)
    reports the sum instead of the proxy clobbering local counts."""
    for name, entry in snap.items():
        kwargs = {"tag_keys": entry.get("tag_keys", ())}
        if entry["kind"] == "histogram":
            kwargs["boundaries"] = entry.get(
                "boundaries", (0.01, 0.1, 1, 10, 100))
        m = get_or_create(entry["kind"], name,
                          entry.get("description", ""), **kwargs)
        with m._lock:
            m._remote[source] = entry
