"""Storm flight recorder: the last N seconds of spans + a metrics snapshot,
dumped NEXT TO a failing storm artifact.

A storm that trips a violation (or hangs long enough for the faulthandler
watchdog) today leaves an artifact full of AGGREGATES — percentiles and
counters that say *that* it went wrong, not *what was happening*. The
flight record is the missing context: every span whose end falls inside
`tracing_flight_recorder_window_s` (the tracing ring is always recording,
even with distributed propagation off) plus the full process-local metrics
snapshot, written as `<artifact>.flightrec.json` so the two files travel
together into CI artifacts.

Best-effort by construction: a failing dump must never mask the violation
that triggered it — every error is swallowed into the logger and the
caller just gets None.
"""

from __future__ import annotations

import json
import logging
import os
from typing import Any, List, Optional

logger = logging.getLogger(__name__)


def _key(k: Any) -> str:
    if isinstance(k, tuple):
        return ",".join(map(str, k))
    return k if isinstance(k, str) else str(k)


def _json_safe(obj: Any) -> Any:
    """Metrics snapshots key series by TAG-VALUE TUPLES — stringify those
    (and anything else JSON rejects) without losing the tag values."""
    if isinstance(obj, dict):
        return {_key(k): _json_safe(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_json_safe(v) for v in obj]
    if isinstance(obj, (str, int, float, bool)) or obj is None:
        return obj
    return repr(obj)


def flight_record_path(artifact_path: str) -> str:
    return artifact_path + ".flightrec.json"


def dump_flight_record(artifact_path: str,
                       violations: Optional[List[str]] = None,
                       window_s: Optional[float] = None,
                       reason: str = "violations") -> Optional[str]:
    """Write `<artifact>.flightrec.json`; returns the path or None on any
    failure. `reason` distinguishes a violation dump from a watchdog one."""
    from ray_tpu.core.config import get_config
    from ray_tpu.util import metrics, tracing

    try:
        if window_s is None:
            window_s = get_config().tracing_flight_recorder_window_s
        path = flight_record_path(artifact_path)
        record = {
            "reason": reason,
            "violations": list(violations or []),
            "window_s": window_s,
            "pid": os.getpid(),
            "anchor_us": tracing.now_us(),
            "spans": _json_safe(tracing.recent_events(window_s)),
            "metrics": _json_safe(metrics.snapshot()),
        }
        with open(path, "w") as f:
            json.dump(record, f, indent=2, default=repr)
        logger.warning("flight record written to %s (%d spans, %s)",
                       path, len(record["spans"]), reason)
        return path
    except Exception:
        logger.warning("flight record dump failed", exc_info=True)
        return None
