"""joblib backend over the task runtime.

Mirrors the reference's `ray.util.joblib.register_ray`
(`python/ray/util/joblib/__init__.py` + `ray_backend.py`): after
`register_backend()`, `joblib.parallel_backend("ray_tpu")` routes
scikit-learn / joblib.Parallel work through cluster tasks instead of
local processes. Gated on joblib being importable (it ships with
scikit-learn; absent in a minimal image the call raises ImportError).
"""

from __future__ import annotations

import threading
from typing import Any, Callable, List

import ray_tpu


def register_backend() -> None:
    from joblib import register_parallel_backend
    from joblib._parallel_backends import ParallelBackendBase

    class RayTpuBackend(ParallelBackendBase):
        supports_timeout = False
        uses_threads = False
        supports_sharedmem = False

        def configure(self, n_jobs: int = 1, parallel=None, **kwargs) -> int:
            if not ray_tpu.is_initialized():
                ray_tpu.init()
            self.parallel = parallel
            return self.effective_n_jobs(n_jobs)

        def effective_n_jobs(self, n_jobs: int) -> int:
            if n_jobs in (None, -1):
                return int(ray_tpu.cluster_resources().get("CPU", 1))
            return max(1, n_jobs)

        def apply_async(self, func: Callable, callback=None):
            @ray_tpu.remote
            def _run(f):
                return f()

            ref = _run.remote(func)
            result = _ImmediateResult(ref)
            if callback is not None:
                # fire the completion callback when the task actually
                # finishes (a synchronous callback would make joblib's
                # dispatcher believe every batch completes instantly and
                # flood the queue / collapse batch-size auto-tuning)
                def _notify():
                    try:
                        ray_tpu.wait([ref], num_returns=1, timeout=None)
                    finally:
                        callback(result)

                threading.Thread(target=_notify, daemon=True).start()
            return result

        def abort_everything(self, ensure_ready: bool = True) -> None:
            pass

    register_parallel_backend("ray_tpu", RayTpuBackend)


class _ImmediateResult:
    """joblib future shim: joblib calls .get() to collect the batch."""

    def __init__(self, ref: Any):
        self._ref = ref

    def get(self, timeout: float = None) -> List[Any]:
        return ray_tpu.get(self._ref, timeout=timeout)
