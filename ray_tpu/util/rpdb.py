"""Remote pdb: breakpoints inside tasks/actors, attachable from the CLI.

Reference parity: `ray debug` + python/ray/util/rpdb.py — a task calls
`ray_tpu.util.rpdb.set_trace()`, which opens a TCP-bound pdb session,
registers it in the GCS KV (host, port, task context), and blocks until a
client attaches. `ray_tpu debug --address <gcs>` lists active breakpoints
and connects the terminal to one (plain socket I/O — `nc host port` works
too).
"""

from __future__ import annotations

import json
import pdb
import socket
import sys
import threading
from typing import List, Optional

_KV_NS = "rpdb"


class _SocketIO:
    """File-like adapter binding pdb's stdin/stdout to one connection."""

    def __init__(self, conn: socket.socket):
        self._conn = conn
        self._rfile = conn.makefile("r")
        self._wfile = conn.makefile("w")

    def readline(self):
        return self._rfile.readline()

    def write(self, data):
        self._wfile.write(data)
        return len(data)

    def flush(self):
        try:
            self._wfile.flush()
        except OSError:
            pass  # peer hung up mid-session

    def close(self):
        for f in (self._rfile, self._wfile):
            try:
                f.close()
            except OSError:
                pass
        try:
            self._conn.close()
        except OSError:
            pass


class _RemotePdb(pdb.Pdb):
    """pdb over a socket; cleanup (KV deregister + socket close) runs when
    the session ends — NOT in set_trace's own frame, or the debugger would
    stop inside the cleanup code instead of the user's."""

    def __init__(self, io: _SocketIO, on_done=None):
        super().__init__(stdin=io, stdout=io)
        self.use_rawinput = False
        self.prompt = "(rpdb) "
        self._on_done = on_done

    def _cleanup(self):
        cb, self._on_done = self._on_done, None
        if cb:
            try:
                cb()
            except Exception:
                pass

    def do_continue(self, arg):
        out = super().do_continue(arg)
        self._cleanup()
        return out

    do_c = do_cont = do_continue

    def do_quit(self, arg):
        out = super().do_quit(arg)
        self._cleanup()
        return out

    do_q = do_exit = do_quit

    def do_EOF(self, arg):
        out = super().do_EOF(arg)
        self._cleanup()
        return out


def _register(entry: dict) -> Optional[str]:
    """Record the breakpoint in the GCS KV so the CLI can list it."""
    try:
        from ray_tpu.core.worker import current_worker

        w = current_worker()
        if w is None:
            return None
        key = f"bp-{entry['host']}:{entry['port']}".encode()
        w.gcs.call("kv_put", {"namespace": _KV_NS, "key": key,
                              "value": json.dumps(entry).encode()})
        return key.decode()
    except (OSError, RuntimeError, TimeoutError):  # GCS unreachable
        return None


def _unregister(key: Optional[str]) -> None:
    if key is None:
        return
    try:
        from ray_tpu.core.worker import current_worker

        w = current_worker()
        if w is not None:
            w.gcs.call("kv_del", {"namespace": _KV_NS, "key": key.encode()})
    except (OSError, RuntimeError, TimeoutError):
        pass  # breakpoint entry ages out of the KV anyway


def set_trace(frame=None) -> None:
    """Open a remote-attachable breakpoint and block until a debugger
    client connects (reference rpdb behavior)."""
    import os

    server = socket.socket()
    server.bind(("127.0.0.1", 0))
    server.listen(1)
    host, port = server.getsockname()
    entry = {"host": host, "port": port, "pid": os.getpid()}
    try:
        from ray_tpu.core.worker import current_worker

        w = current_worker()
        if w is not None:
            tid = getattr(w._tls, "task_id", None)
            entry["task_id"] = tid.binary().hex() if tid else None
            entry["actor_id"] = (w.actor_id.binary().hex()
                                 if w.actor_id else None)
    except Exception:
        pass
    key = _register(entry)
    sys.stderr.write(
        f"rpdb waiting for attach at {host}:{port} "
        f"(ray_tpu debug --address <gcs>, or `nc {host} {port}`)\n")
    conn, _ = server.accept()
    io = _SocketIO(conn)

    def on_done():
        _unregister(key)
        io.close()
        server.close()

    dbg = _RemotePdb(io, on_done=on_done)
    dbg.set_trace(frame or sys._getframe().f_back)
    # the debugger owns the session from here; cleanup fires on c/q/EOF


def list_breakpoints(gcs_client) -> List[dict]:
    """Active breakpoints from the GCS KV (for the CLI)."""
    out = []
    try:
        keys = gcs_client.call("kv_keys", {"namespace": _KV_NS,
                                           "prefix": b""})
        for key in keys:
            value = gcs_client.call("kv_get", {"namespace": _KV_NS,
                                               "key": key})
            if value is None:
                continue
            try:
                out.append(json.loads(bytes(value).decode()))
            except (ValueError, UnicodeDecodeError):
                continue  # stale/corrupt registry entry
    except Exception:
        pass
    return out


def attach(host: str, port: int) -> None:
    """Bridge this terminal to a remote pdb session."""
    conn = socket.create_connection((host, port))
    stop = threading.Event()

    def pump_in():
        try:
            while not stop.is_set():
                line = sys.stdin.readline()
                if not line:
                    break
                conn.sendall(line.encode())
        except (OSError, EOFError, KeyboardInterrupt):
            pass  # debugger detach closes the socket mid-pipe

    t = threading.Thread(target=pump_in, daemon=True)
    t.start()
    try:
        while True:
            data = conn.recv(4096)
            if not data:
                break
            sys.stdout.write(data.decode(errors="replace"))
            sys.stdout.flush()
    finally:
        stop.set()
        conn.close()
