"""Exponential backoff with full jitter.

One policy for every reconnect/retry loop in the runtime (reference
`exponential_backoff.h` + the AWS "full jitter" scheme): the delay for
attempt `n` is drawn uniformly from `[0, min(cap, base * factor**n)]`.
Full jitter decorrelates a thundering herd — after a head replacement
every raylet, worker and driver reconnects at once, and fixed sleeps
would re-synchronize them against the new address forever.

Used by `rpc.ReconnectingClient` (control-plane links, owner links),
`ResultBuffer`'s owner-down requeue, and the serve controller's
checkpoint restore. Pass a seeded `random.Random` as `rng` for
deterministic tests.
"""

from __future__ import annotations

import random
import time
from typing import Optional


class ExponentialBackoff:
    """Stateful attempt counter + full-jitter delay schedule."""

    def __init__(self, base_s: float = 0.1, cap_s: float = 10.0,
                 factor: float = 2.0,
                 rng: Optional[random.Random] = None):
        if base_s <= 0:
            raise ValueError("base_s must be > 0")
        self.base_s = base_s
        self.cap_s = max(base_s, cap_s)
        self.factor = factor
        self._rng = rng or random
        self._attempt = 0

    @property
    def attempt(self) -> int:
        return self._attempt

    def delay_for(self, attempt: int) -> float:
        """Full-jitter delay for a given attempt number (stateless)."""
        ceiling = min(self.cap_s, self.base_s * (self.factor ** max(0, attempt)))
        return self._rng.uniform(0.0, ceiling)

    def next_delay(self) -> float:
        """Delay for the current attempt; advances the counter."""
        d = self.delay_for(self._attempt)
        self._attempt += 1
        return d

    def sleep(self) -> float:
        """Sleep for the next delay; returns the slept duration."""
        d = self.next_delay()
        if d > 0:
            time.sleep(d)
        return d

    def reset(self) -> None:
        self._attempt = 0
