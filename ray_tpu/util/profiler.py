"""On-demand, dependency-free CPU and memory profiling for live workers.

The reference dashboard launches py-spy / memray against a worker pid on
demand (`dashboard/modules/reporter/profile_manager.py`). Neither tool is
assumed here; the same capability is built from the runtime itself:

  * CPU: an in-process sampling profiler — a daemon thread walks
    ``sys._current_frames()`` every ``interval`` seconds for ``duration``
    seconds and aggregates collapsed stacks (the folded format flamegraph
    tooling eats directly, one ``func;func;func count`` line each).
  * Memory: a ``tracemalloc`` window — tracing is switched on for the
    duration, and the report is the top allocation sites of everything
    still live at the end of the window, plus RSS before/after.

Both run *inside* the target worker (triggered by a raylet push, results
written to a per-request file the raylet serves back), so no ptrace
capability or external binary is needed — which also makes this work in
containers where py-spy's process_vm_readv is blocked.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from typing import Any, Dict, Optional

PROFILE_DIR = "/tmp/ray_tpu/profiles"


def sample_cpu(duration_s: float, interval_s: float = 0.01,
               max_stacks: int = 200) -> Dict[str, Any]:
    """Sample every thread's Python stack for duration_s; returns collapsed
    stacks sorted by sample count (the hottest first)."""
    me = threading.get_ident()
    counts: Dict[str, int] = {}
    n_samples = 0
    deadline = time.monotonic() + duration_s
    while time.monotonic() < deadline:
        for tid, frame in sys._current_frames().items():
            if tid == me:
                continue
            parts = []
            f = frame
            while f is not None:
                code = f.f_code
                parts.append(f"{code.co_name} ({code.co_filename}:{f.f_lineno})")
                f = f.f_back
            stack = ";".join(reversed(parts))
            counts[stack] = counts.get(stack, 0) + 1
        n_samples += 1
        time.sleep(interval_s)
    top = sorted(counts.items(), key=lambda kv: -kv[1])[:max_stacks]
    return {
        "kind": "cpu",
        "pid": os.getpid(),
        "duration_s": duration_s,
        "interval_s": interval_s,
        "n_samples": n_samples,
        "stacks": [{"stack": s, "count": c} for s, c in top],
    }


def _rss_bytes() -> Optional[int]:
    try:
        with open("/proc/self/statm") as fh:
            return int(fh.read().split()[1]) * os.sysconf("SC_PAGE_SIZE")
    except (OSError, ValueError, IndexError):
        return None


def sample_memory(duration_s: float, top_n: int = 50) -> Dict[str, Any]:
    """Trace allocations for duration_s; report the top sites still live at
    the end of the window (tracemalloc only sees allocations made while
    tracing, so this is the reference's memray "live window" analog, not a
    full-heap census)."""
    import tracemalloc

    owned = not tracemalloc.is_tracing()
    rss_before = _rss_bytes()
    if owned:
        tracemalloc.start(16)
    try:
        time.sleep(duration_s)
        snap = tracemalloc.take_snapshot()
    finally:
        if owned:
            tracemalloc.stop()
    stats = snap.statistics("traceback")[:top_n]
    return {
        "kind": "memory",
        "pid": os.getpid(),
        "duration_s": duration_s,
        "rss_before": rss_before,
        "rss_after": _rss_bytes(),
        "note": "allocations made during the window and still live at its end",
        "sites": [{
            "size_bytes": st.size,
            "count": st.count,
            "traceback": [str(line) for line in st.traceback.format()],
        } for st in stats],
    }


def run_profile_request(payload: Dict[str, Any]) -> None:
    """Entry point for the worker's "profile" push: profile THIS process in
    a background thread and drop the JSON where the raylet can serve it."""
    token = payload["token"]
    kind = payload.get("profile_kind", "cpu")
    duration = min(float(payload.get("duration_s", 5.0)), 120.0)

    def work():
        try:
            if kind == "memory":
                result = sample_memory(duration)
            else:
                result = sample_cpu(duration)
        except Exception as e:  # the result file must always appear
            result = {"kind": kind, "pid": os.getpid(),
                      "error": f"{type(e).__name__}: {e}"}
        os.makedirs(PROFILE_DIR, exist_ok=True)
        _sweep_stale()
        path = os.path.join(PROFILE_DIR, f"{token}.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(result, fh)
        os.replace(tmp, path)  # atomic: pollers never see a partial file

    threading.Thread(target=work, name="profile-request", daemon=True).start()


def trigger_profile(gcs, pid, kind: str, duration_s: float):
    """Fan a profile_worker request out to every alive raylet; returns
    [(node_address, pid, token)]. Shared by the CLI and the dashboard —
    a node dying between the GCS listing and the connect is survived
    (its workers simply don't report)."""
    from ray_tpu.core import rpc as _rpc

    started = []
    for n in gcs.call("get_all_nodes", timeout=10):
        if not n["alive"]:
            continue
        try:
            c = _rpc.connect_with_retry(n["address"], timeout=5)
        except ConnectionError:
            continue  # raced a node death; the alive list was stale
        try:
            out = c.call("profile_worker", {
                "pid": pid, "profile_kind": kind, "duration_s": duration_s})
        except (ConnectionError, OSError, TimeoutError):
            continue
        finally:
            c.close()
        for s in out.get("started", []):
            started.append((n["address"], s["pid"], s["token"]))
    return started


def poll_profile_results(pending, deadline_monotonic: float,
                         poll_interval_s: float = 1.0):
    """Collect finished profiles for [(addr, pid, token)] tuples until all
    report or the deadline passes; returns (reports, still_pending).
    A node dying mid-profile costs only its own reports."""
    from ray_tpu.core import rpc as _rpc

    reports = []
    pending = list(pending)
    while pending and time.monotonic() < deadline_monotonic:
        time.sleep(poll_interval_s)
        still = []
        for addr, pid, token in pending:
            try:
                c = _rpc.connect_with_retry(addr, timeout=5)
            except ConnectionError:
                continue  # node died; drop its token
            try:
                r = c.call("profile_result", {"token": token})
            except (ConnectionError, OSError, TimeoutError):
                continue
            finally:
                c.close()
            if r.get("result") is None:
                still.append((addr, pid, token))
            else:
                reports.append(r["result"])
        pending = still
    return reports, pending


def _sweep_stale(max_age_s: float = 600.0) -> None:
    """Reclaim result files whose caller never collected them (timed out,
    crashed): without this, periodic dashboard profiling grows the dir
    one file per worker per request forever."""
    cutoff = time.time() - max_age_s
    try:
        names = os.listdir(PROFILE_DIR)
    except OSError:
        return
    for name in names:
        path = os.path.join(PROFILE_DIR, name)
        try:
            if os.path.getmtime(path) < cutoff:
                os.unlink(path)
        except OSError:
            pass  # concurrent sweep/read; someone else won


def read_profile_result(token: str) -> Optional[Dict[str, Any]]:
    """Raylet-side: the finished profile for token, or None while running.
    The file is deleted on a successful read — each token is collected
    exactly once."""
    if not token.replace("-", "").isalnum():  # tokens name files; no paths
        raise ValueError(f"bad profile token {token!r}")
    path = os.path.join(PROFILE_DIR, f"{token}.json")
    try:
        with open(path) as fh:
            result = json.load(fh)
    except FileNotFoundError:
        return None
    try:
        os.unlink(path)
    except OSError:
        pass
    return result
