"""Built-in timeline: chrome://tracing events.

Equivalent of the reference's profile-event timeline
(`src/ray/core_worker/profile_event.h` -> `ray.timeline()`,
`python/ray/_private/state.py:851 chrome_tracing_dump:435`): lightweight
in-process event recording, dumped as chrome trace JSON.
"""

from __future__ import annotations

import json
import os
import threading
import time
from contextlib import contextmanager
from typing import List, Optional

_events: List[dict] = []
_lock = threading.Lock()
_t0 = time.perf_counter()
# observers called with each completed span dict — the OpenTelemetry
# bridge (util/otel.py) and the worker's GCS profile-event shipper hook in
# here (reference: opt-in OTel spans + TaskEventBuffer profile events)
_span_hooks: List = []


def add_span_hook(fn) -> None:
    with _lock:
        if fn not in _span_hooks:
            _span_hooks.append(fn)


def remove_span_hook(fn) -> None:
    with _lock:
        if fn in _span_hooks:
            _span_hooks.remove(fn)


def _now_us() -> float:
    return (time.perf_counter() - _t0) * 1e6


@contextmanager
def span(name: str, category: str = "task", **args):
    start = _now_us()
    try:
        yield
    finally:
        end = _now_us()
        event = {
            "name": name, "cat": category, "ph": "X",
            "ts": start, "dur": end - start,
            "pid": os.getpid(), "tid": threading.get_ident() % 100000,
            "args": args,
        }
        with _lock:
            _events.append(event)
            hooks = list(_span_hooks)
        for h in hooks:
            try:
                h(event)
            except Exception:  # user hook: never let tracing kill the task
                pass


def instant(name: str, category: str = "event", **args) -> None:
    with _lock:
        _events.append({
            "name": name, "cat": category, "ph": "i", "ts": _now_us(),
            "pid": os.getpid(), "tid": threading.get_ident() % 100000,
            "s": "p", "args": args,
        })


def get_events() -> List[dict]:
    with _lock:
        return list(_events)


def dump(path: str, extra_events: Optional[List[dict]] = None) -> None:
    events = get_events() + list(extra_events or [])
    with open(path, "w") as f:
        json.dump({"traceEvents": events}, f)


def clear() -> None:
    with _lock:
        _events.clear()
