"""Built-in timeline: chrome://tracing events + distributed trace context.

Equivalent of the reference's profile-event timeline
(`src/ray/core_worker/profile_event.h` -> `ray.timeline()`,
`python/ray/_private/state.py:851 chrome_tracing_dump:435`): lightweight
in-process event recording, dumped as chrome trace JSON.

Two properties make multi-process merges meaningful:

- **Epoch anchor.** Timestamps are wall-epoch MICROSECONDS, derived as
  `_epoch_us + (perf_counter() - _t0)`: one `(time.time(), perf_counter())`
  pair captured at import anchors the monotonic clock to the epoch, so
  spans are monotone within a process AND directly comparable across
  processes on one host. Cross-NODE skew is corrected at merge time from
  per-source clock offsets (task_events.py estimates them NTP-style from
  an RPC round-trip to the GCS).

- **Bounded ring.** The in-process buffer is capped
  (`tracing_max_buffer_size`, mirroring `task_events_max_buffer_size`):
  overflow drops the OLDEST spans and counts them; `drain()` hands the
  dropped count to the TaskEventBuffer so it rides the next flush and the
  GCS-side truncation accounting stays honest.

Trace context (the distributed half, gated on `tracing_enabled`): a
thread-local `(trace_id, parent_span_id)` pair. `span()` records both ids
plus its own fresh span_id on the event and re-parents nested spans under
itself; `ctx_scope()` adopts a context that crossed a process boundary
(TaskSpec.trace_ctx), making driver submit -> raylet lease -> worker
execute -> result delivery one causal tree under a single trace_id.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Deque, List, Optional, Tuple

_events: Deque[dict] = deque()
_lock = threading.Lock()
# epoch anchor: one wall/monotonic pair per process. perf_counter gives
# monotonicity (time.time() can step under NTP slew); the epoch term makes
# the absolute values line up across processes.
_t0 = time.perf_counter()
_epoch_us = time.time() * 1e6
_dropped = 0          # ring overflow since the last drain()
_total = 0            # events ever appended (drain cursors index into this)
# observers called with each completed span dict — the OpenTelemetry
# bridge (util/otel.py) and the worker's GCS profile-event shipper hook in
# here (reference: opt-in OTel spans + TaskEventBuffer profile events)
_span_hooks: List = []

_tls = threading.local()


def add_span_hook(fn) -> None:
    with _lock:
        if fn not in _span_hooks:
            _span_hooks.append(fn)


def remove_span_hook(fn) -> None:
    with _lock:
        if fn in _span_hooks:
            _span_hooks.remove(fn)


def _now_us() -> float:
    return _epoch_us + (time.perf_counter() - _t0) * 1e6


def now_us() -> float:
    """Epoch-anchored wall microseconds, monotone within this process."""
    return _now_us()


# --------------------------------------------------------------- trace ctx
def enabled() -> bool:
    """Whether distributed trace-context propagation is on (default off:
    local spans still record, but no ids are minted or shipped on specs)."""
    from ray_tpu.core.config import get_config

    return get_config().tracing_enabled


def new_id() -> str:
    return os.urandom(8).hex()


def current_ctx() -> Optional[Tuple[str, str]]:
    """The thread's (trace_id, parent_span_id) or None outside a trace."""
    return getattr(_tls, "ctx", None)


def set_ctx(ctx: Optional[Tuple[str, str]]) -> None:
    _tls.ctx = tuple(ctx) if ctx else None


def start_trace() -> Tuple[str, str]:
    """Begin a new trace on this thread; returns (trace_id, "") — the empty
    parent marks subsequent spans as roots of the tree."""
    ctx = (new_id(), "")
    _tls.ctx = ctx
    return ctx


@contextmanager
def ctx_scope(ctx: Optional[Tuple[str, str]]):
    """Adopt a context that crossed a process/thread boundary (a
    TaskSpec.trace_ctx, a router request's captured ctx) for the duration
    of the block. None is a no-op so call sites need no conditional."""
    if not ctx:
        yield
        return
    prev = getattr(_tls, "ctx", None)
    _tls.ctx = tuple(ctx)
    try:
        yield
    finally:
        _tls.ctx = prev


def _append(event: dict) -> None:
    """Caller must NOT hold _lock. Ring-bounded append + hook fanout."""
    global _dropped, _total
    from ray_tpu.core.config import get_config

    limit = max(1, get_config().tracing_max_buffer_size)
    with _lock:
        _events.append(event)
        _total += 1
        while len(_events) > limit:
            _events.popleft()
            _dropped += 1
        # hooks observe completed SPANS only (the OTel bridge reads "dur")
        hooks = list(_span_hooks) if event.get("ph") == "X" else ()
    for h in hooks:
        try:
            h(event)
        except Exception:  # user hook: never let tracing kill the task
            pass


@contextmanager
def span(name: str, category: str = "task", **args):
    start = _now_us()
    ctx = getattr(_tls, "ctx", None)
    sid = prev = None
    if ctx is not None:
        sid = new_id()
        prev = ctx
        _tls.ctx = (ctx[0], sid)  # nested spans parent under this one
    try:
        yield
    finally:
        end = _now_us()
        if sid is not None:
            _tls.ctx = prev
        event = {
            "name": name, "cat": category, "ph": "X",
            "ts": start, "dur": end - start,
            "pid": os.getpid(), "tid": threading.get_ident() % 100000,
            "args": args,
        }
        if sid is not None:
            event["trace_id"] = ctx[0]
            event["span_id"] = sid
            event["parent_id"] = ctx[1]
        _append(event)


def add_complete(name: str, category: str, start_us: float, dur_us: float,
                 trace_id: Optional[str] = None,
                 span_id: Optional[str] = None,
                 parent_id: Optional[str] = None, **args) -> None:
    """Record a complete ("X") span with explicit timing/ids — for call
    sites that measure a window themselves (raylet queue wait, dispatch
    latency, serve ingress) rather than wrapping a block."""
    event = {
        "name": name, "cat": category, "ph": "X",
        "ts": start_us, "dur": max(0.0, dur_us),
        "pid": os.getpid(), "tid": threading.get_ident() % 100000,
        "args": args,
    }
    if trace_id:
        event["trace_id"] = trace_id
        event["span_id"] = span_id or new_id()
        event["parent_id"] = parent_id or ""
    _append(event)


def instant(name: str, category: str = "event", **args) -> None:
    _append({
        "name": name, "cat": category, "ph": "i", "ts": _now_us(),
        "pid": os.getpid(), "tid": threading.get_ident() % 100000,
        "s": "p", "args": args,
    })


def get_events() -> List[dict]:
    with _lock:
        return list(_events)


def drain(cursor: int) -> Tuple[List[dict], int, int]:
    """Events appended since `cursor` (a running sequence number), the new
    cursor, and how many of them overflowed the ring before this drain
    could ship them (NOT the raw eviction count — already-drained spans
    falling off the left edge are not a loss). The shipping path
    (TaskEventBuffer) uses this instead of list slicing so a ring overflow
    between flushes can never silently skew the window. A cursor from
    before a clear() (cursor > total) resyncs to the start."""
    global _dropped
    with _lock:
        if cursor > _total:
            cursor = 0  # clear() ran; resync
        start_seq = _total - len(_events)
        skipped = max(0, start_seq - cursor)
        fresh = list(_events)[max(0, cursor - start_seq):]
        _dropped = 0
        return fresh, _total, skipped


def recent_events(window_s: float) -> List[dict]:
    """Spans whose END falls within the last `window_s` seconds — the
    flight-recorder slice dumped next to a failed storm artifact."""
    floor = _now_us() - window_s * 1e6
    with _lock:
        return [e for e in _events
                if e.get("ts", 0) + e.get("dur", 0) >= floor]


def dump(path: str, extra_events: Optional[List[dict]] = None) -> None:
    events = get_events() + list(extra_events or [])
    with open(path, "w") as f:
        json.dump({"traceEvents": events}, f)


def clear() -> None:
    global _dropped, _total
    with _lock:
        _events.clear()
        _dropped = 0
        _total = 0
