"""Built-in timeline: chrome://tracing events.

Equivalent of the reference's profile-event timeline
(`src/ray/core_worker/profile_event.h` -> `ray.timeline()`,
`python/ray/_private/state.py:851 chrome_tracing_dump:435`): lightweight
in-process event recording, dumped as chrome trace JSON.
"""

from __future__ import annotations

import json
import os
import threading
import time
from contextlib import contextmanager
from typing import List

_events: List[dict] = []
_lock = threading.Lock()
_t0 = time.perf_counter()


def _now_us() -> float:
    return (time.perf_counter() - _t0) * 1e6


@contextmanager
def span(name: str, category: str = "task", **args):
    start = _now_us()
    try:
        yield
    finally:
        end = _now_us()
        with _lock:
            _events.append({
                "name": name, "cat": category, "ph": "X",
                "ts": start, "dur": end - start,
                "pid": os.getpid(), "tid": threading.get_ident() % 100000,
                "args": args,
            })


def instant(name: str, category: str = "event", **args) -> None:
    with _lock:
        _events.append({
            "name": name, "cat": category, "ph": "i", "ts": _now_us(),
            "pid": os.getpid(), "tid": threading.get_ident() % 100000,
            "s": "p", "args": args,
        })


def get_events() -> List[dict]:
    with _lock:
        return list(_events)


def dump(path: str) -> None:
    with open(path, "w") as f:
        json.dump({"traceEvents": get_events()}, f)


def clear() -> None:
    with _lock:
        _events.clear()
