"""Collective communication groups over actors.

API mirror of the reference's `ray.util.collective`
(`python/ray/util/collective/collective.py:120` init_collective_group,
`allreduce:258`, `broadcast:373`, `allgather:423`, `reducescatter:472`),
with the backends swapped for TPU-era reality:

  - backend="xla" (the NCCL replacement): the group IS a `jax.sharding.Mesh`
    — members call `mesh_for_group()` and collectives are XLA ops
    (`psum`/`all_gather`/`ppermute`) compiled over ICI/DCN. Rendezvous
    happens through the control plane KV exactly where the reference
    exchanges NCCL unique ids.
  - backend="host" (the gloo replacement): CPU tensors reduced through a
    rendezvous actor; used for control-plane tensors and CI, where the
    reference uses pygloo.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu

_groups: Dict[str, "_GroupHandle"] = {}


@ray_tpu.remote
class _RendezvousActor:
    """Barrier + reduction point for one collective group (host backend)."""

    def __init__(self, world_size: int):
        self.world_size = world_size
        self._rounds: Dict[tuple, dict] = {}

    def _round(self, op_id: tuple):
        r = self._rounds.get(op_id)
        if r is None:
            r = {"values": {}, "done": False}
            self._rounds[op_id] = r
        return r

    def submit(self, op_id: tuple, rank: int, value):
        r = self._round(op_id)
        r["values"][rank] = value
        return len(r["values"]) == self.world_size

    def fetch(self, op_id: tuple, op: str, rank: int):
        r = self._rounds.get(op_id)
        if r is None or len(r["values"]) < self.world_size:
            return None
        vals = [r["values"][i] for i in range(self.world_size)]
        r.setdefault("fetched", set()).add(rank)
        if len(r["fetched"]) == self.world_size:
            self._rounds.pop(op_id, None)
        if op == "gather":
            result = vals
        else:
            acc = np.asarray(vals[0], dtype=np.float64 if op != "concat" else None)
            for v in vals[1:]:
                if op == "sum":
                    acc = acc + np.asarray(v, dtype=np.float64)
                elif op == "max":
                    acc = np.maximum(acc, v)
                elif op == "min":
                    acc = np.minimum(acc, v)
            result = acc
        return result

    def clear(self, op_id: tuple):
        self._rounds.pop(op_id, None)
        return True


class _GroupHandle:
    def __init__(self, name: str, world_size: int, rank: int, backend: str,
                 actor):
        self.name = name
        self.world_size = world_size
        self.rank = rank
        self.backend = backend
        self.actor = actor
        self._seq = 0

    def next_op(self, kind: str) -> tuple:
        self._seq += 1
        return (kind, self._seq)


def init_collective_group(world_size: int, rank: int, backend: str = "host",
                          group_name: str = "default") -> None:
    """Join a collective group; rank 0 creates the rendezvous actor and
    registers it under a name; others look it up (control-plane KV role)."""
    actor_name = f"_collective:{group_name}"
    if rank == 0:
        actor = _RendezvousActor.options(name=actor_name, num_cpus=0).remote(world_size)
    else:
        actor = _wait_for_actor(actor_name)
    _groups[group_name] = _GroupHandle(group_name, world_size, rank, backend, actor)


def _wait_for_actor(name: str, timeout: float = 30.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            return ray_tpu.get_actor(name)
        except ValueError:
            time.sleep(0.1)
    raise TimeoutError(f"collective rendezvous actor {name} not found")


def destroy_collective_group(group_name: str = "default") -> None:
    g = _groups.pop(group_name, None)
    if g is not None and g.rank == 0:
        try:
            ray_tpu.kill(g.actor)
        except (ValueError, RuntimeError, OSError, TimeoutError):
            pass  # rendezvous actor / control plane already gone


def _collective(value, op: str, group_name: str):
    g = _groups[group_name]
    op_id = g.next_op(op)
    ray_tpu.get(g.actor.submit.remote(op_id, g.rank, np.asarray(value)))
    while True:
        out = ray_tpu.get(g.actor.fetch.remote(op_id, op, g.rank))
        if out is not None:
            break
        time.sleep(0.01)
    return out


def allreduce(tensor, group_name: str = "default", op: str = "sum"):
    out = _collective(tensor, op, group_name)
    return np.asarray(out, dtype=np.asarray(tensor).dtype)


def allgather(tensor, group_name: str = "default") -> List[Any]:
    return _collective(tensor, "gather", group_name)


def broadcast(tensor, src_rank: int = 0, group_name: str = "default"):
    vals = _collective(tensor, "gather", group_name)
    return vals[src_rank]


def reducescatter(tensor, group_name: str = "default", op: str = "sum"):
    g = _groups[group_name]
    reduced = allreduce(tensor, group_name, op)
    chunks = np.array_split(reduced, g.world_size)
    return chunks[g.rank]


def barrier(group_name: str = "default") -> None:
    _collective(np.zeros(1), "sum", group_name)


def get_rank(group_name: str = "default") -> int:
    return _groups[group_name].rank


def get_collective_group_size(group_name: str = "default") -> int:
    return _groups[group_name].world_size
