"""ActorPool: load-balance tasks over a fixed set of actors.

Mirrors `ray.util.ActorPool` (reference `python/ray/util/actor_pool.py`).
"""

from __future__ import annotations

from typing import Any, Callable, List

import ray_tpu


class ActorPool:
    def __init__(self, actors: List[Any]):
        self._idle = list(actors)
        self._future_to_actor = {}
        self._pending_submits: List[tuple] = []
        self._result_queue: List[Any] = []

    def submit(self, fn: Callable, value: Any) -> None:
        if self._idle:
            actor = self._idle.pop()
            ref = fn(actor, value)
            self._future_to_actor[ref] = actor
        else:
            self._pending_submits.append((fn, value))

    def get_next(self, timeout: float = None) -> Any:
        if not self._future_to_actor:
            raise StopIteration("no pending results")
        refs = list(self._future_to_actor)
        ready, _ = ray_tpu.wait(refs, num_returns=1, timeout=timeout)
        if not ready:
            raise TimeoutError("get_next timed out")
        ref = ready[0]
        actor = self._future_to_actor.pop(ref)
        self._return_actor(actor)
        return ray_tpu.get(ref)

    def get_next_unordered(self, timeout: float = None) -> Any:
        return self.get_next(timeout)

    def _return_actor(self, actor) -> None:
        if self._pending_submits:
            fn, value = self._pending_submits.pop(0)
            ref = fn(actor, value)
            self._future_to_actor[ref] = actor
        else:
            self._idle.append(actor)

    def map(self, fn: Callable, values: List[Any]):
        for v in values:
            self.submit(fn, v)
        for _ in values:
            yield self.get_next()

    def map_unordered(self, fn: Callable, values: List[Any]):
        return self.map(fn, values)

    def has_next(self) -> bool:
        return bool(self._future_to_actor)

    def has_free(self) -> bool:
        return bool(self._idle)
