"""`ray_tpu.util`: parity with `ray.util` (reference `python/ray/util/`).

Exposes placement groups (`python/ray/util/placement_group.py:136`),
ActorPool (`python/ray/util/actor_pool.py`), scheduling strategies
(`python/ray/util/scheduling_strategies.py:15,41`), metrics facade
(`python/ray/util/metrics.py`), and the collective namespace.
"""

from ray_tpu.core.placement_group import (
    PlacementGroup,
    placement_group,
    placement_group_table,
    remove_placement_group,
    get_current_placement_group,
)
from ray_tpu.util.actor_pool import ActorPool
from ray_tpu.util import collective
from ray_tpu.util import metrics
from ray_tpu.util import queue
from ray_tpu.util import multiprocessing

__all__ = [
    "PlacementGroup",
    "placement_group",
    "placement_group_table",
    "remove_placement_group",
    "get_current_placement_group",
    "ActorPool",
    "collective",
    "metrics",
    "queue",
    "multiprocessing",
]
