"""Fleet-merged chrome traces: offset alignment, merge, and validation.

The raw material is the span stream every process ships through the
`task_events_batch` channel (util/tracing.py -> core/task_events.py ->
gcs.py): epoch-anchored microsecond stamps tagged with a `_src` (worker or
node hex id) and, per source, an NTP-style clock offset estimated against
the GCS clock. This module is the merge half:

- `apply_offsets` rebases every span onto the GCS clock
  (`ts + offset[src]`), so one chrome timeline lines up across nodes;
- `merge_chrome` produces the chrome://tracing document
  (`{"traceEvents": [...]}`, "X" events with ts/dur in microseconds —
  extra keys like trace_id/span_id ride along, chrome ignores them);
- `validate_chrome` / `validate_chains` are the CI-facing checks: a
  structurally valid document, and per-trace parent links that all
  resolve (every span's parent_id names a span in the same trace, at
  least one root) — the "complete correctly-parented chain" assertion
  the traced storm makes per accepted request;
- `stage_segments` slices one task's spans into the critical-path stages
  (submit -> lease -> dispatch -> execution -> result-deliver) for the
  `ray_tpu trace <task_id>` CLI.
"""

from __future__ import annotations

import json
import math
from typing import Dict, Iterable, List, Optional, Tuple

# critical-path stage order for one task (categories stamped by
# worker.py / raylet.py); serve/rl categories hang off the same tree but
# are not per-task stages
STAGE_ORDER = ("task_submit", "task_lease", "task_dispatch",
               "task_execution", "task_result")


def apply_offsets(spans: Iterable[dict],
                  offsets: Dict[str, float]) -> List[dict]:
    """Rebase spans onto the GCS clock: `offset = gcs_clock - src_clock`
    (the sign task_events.py's probe produces), so aligned ts = ts +
    offset. Sources without an estimate (same process as the GCS, or a
    probe that never completed) pass through unshifted. Returns copies."""
    out = []
    for s in spans:
        off = offsets.get(s.get("_src", ""), 0.0)
        if off:
            s = {**s, "ts": s.get("ts", 0.0) + off}
        else:
            s = dict(s)
        out.append(s)
    return out


def merge_chrome(spans: Iterable[dict],
                 offsets: Optional[Dict[str, float]] = None) -> dict:
    """One chrome-trace document from many sources' spans, clock-aligned
    and time-sorted. Drops nothing: non-span phases ("i" instants) merge
    too, chrome renders them as markers."""
    aligned = apply_offsets(spans, offsets or {})
    aligned.sort(key=lambda e: (e.get("ts", 0.0),
                                e.get("pid", 0), e.get("tid", 0)))
    return {"traceEvents": aligned}


def validate_chrome(doc: dict) -> List[str]:
    """Structural problems with a chrome-trace document (empty list =
    valid): JSON-serializable, a traceEvents list, every event carrying
    name/ph/ts/pid/tid with finite stamps, "X" events with non-negative
    dur, and ts non-decreasing in document order (merge_chrome sorts, so
    a violation means the merge or an offset went wrong)."""
    problems: List[str] = []
    try:
        doc = json.loads(json.dumps(doc))
    except (TypeError, ValueError) as e:
        return [f"not JSON-serializable: {e}"]
    events = doc.get("traceEvents")
    if not isinstance(events, list):
        return ["traceEvents missing or not a list"]
    last_ts = -math.inf
    for i, e in enumerate(events):
        for k in ("name", "ph", "ts", "pid", "tid"):
            if k not in e:
                problems.append(f"event {i} missing {k!r}")
                break
        else:
            ts = e["ts"]
            if not isinstance(ts, (int, float)) or not math.isfinite(ts):
                problems.append(f"event {i} non-finite ts {ts!r}")
                continue
            if e["ph"] == "X":
                dur = e.get("dur")
                if (not isinstance(dur, (int, float))
                        or not math.isfinite(dur) or dur < 0):
                    problems.append(f"event {i} bad dur {dur!r}")
            if ts < last_ts:
                problems.append(
                    f"event {i} ts regresses ({ts} < {last_ts})")
            last_ts = ts
        if len(problems) >= 20:
            problems.append("... (truncated)")
            break
    return problems


def group_by_trace(spans: Iterable[dict]) -> Dict[str, List[dict]]:
    traces: Dict[str, List[dict]] = {}
    for s in spans:
        tid = s.get("trace_id")
        if tid:
            traces.setdefault(tid, []).append(s)
    return traces


def validate_chain(spans: List[dict]) -> dict:
    """One trace's parent-link health: every non-empty parent_id must name
    a span_id IN the trace, ids must be unique, and at least one root
    (parent_id == "") must exist. `processes` counts distinct emitting
    processes (shipping source, falling back to pid) — the storm asserts
    chains span >=3 of them (driver/proxy, raylet, replica worker)."""
    ids = [s.get("span_id") for s in spans if s.get("span_id")]
    idset = set(ids)
    missing = sorted({s.get("parent_id") for s in spans
                      if s.get("parent_id") and
                      s.get("parent_id") not in idset})
    roots = sum(1 for s in spans if s.get("parent_id") == "")
    procs = {s.get("_src") or f"pid:{s.get('pid')}" for s in spans}
    return {
        "spans": len(spans),
        "roots": roots,
        "duplicate_ids": len(ids) - len(idset),
        "missing_parents": missing,
        "processes": len(procs),
        "complete": (len(spans) > 0 and roots >= 1 and not missing
                     and len(ids) == len(idset)),
    }


def validate_chains(spans: Iterable[dict],
                    trace_ids: Optional[Iterable[str]] = None
                    ) -> Dict[str, dict]:
    """validate_chain over every trace present (or the requested ids —
    an id with no spans at all reports as an empty, incomplete chain)."""
    traces = group_by_trace(spans)
    if trace_ids is None:
        keys = list(traces)
    else:
        keys = list(trace_ids)
    return {t: validate_chain(traces.get(t, [])) for t in keys}


def stage_segments(spans: Iterable[dict],
                   task_id: str) -> List[Tuple[str, float, float]]:
    """The critical-path segments of ONE task: `(stage, start_us, dur_us)`
    in STAGE_ORDER for every stage span stamped with this task_id (args
    carry it). Retried tasks can own several spans per stage; all are
    returned, stage-ordered then time-ordered, so gaps between segments
    read as the queue/wire time between stages."""
    rank = {c: i for i, c in enumerate(STAGE_ORDER)}
    segs = []
    for s in spans:
        if s.get("cat") not in rank:
            continue
        if (s.get("args") or {}).get("task_id") != task_id:
            continue
        segs.append((s["cat"], float(s.get("ts", 0.0)),
                     float(s.get("dur", 0.0))))
    segs.sort(key=lambda t: (rank[t[0]], t[1]))
    return segs
