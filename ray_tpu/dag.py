"""Lazy task DAGs: `.bind()` graphs executed on demand.

Mirrors the reference's `ray.dag` substrate (`python/ray/dag/dag_node.py`,
function_node/class_node/input_node): `fn.bind(...)` builds a node without
executing; `node.execute(input)` walks the graph submitting tasks with
upstream ObjectRefs as arguments, so the whole DAG runs as a pipelined set
of tasks. Serve's graph building composes on this.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import ray_tpu


class DAGNode:
    def execute(self, *inputs):
        refs = self._execute(inputs, {})
        return refs

    def _execute(self, inputs, cache):
        raise NotImplementedError


class InputNode(DAGNode):
    """Placeholder for the value passed at execute() time."""

    def __init__(self, index: int = 0):
        self.index = index

    def _execute(self, inputs, cache):
        return inputs[self.index]

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class FunctionNode(DAGNode):
    def __init__(self, remote_fn, args, kwargs):
        self._fn = remote_fn
        self._args = args
        self._kwargs = kwargs

    def _execute(self, inputs, cache):
        key = id(self)
        if key in cache:
            return cache[key]
        args = [a._execute(inputs, cache) if isinstance(a, DAGNode) else a
                for a in self._args]
        kwargs = {k: (v._execute(inputs, cache) if isinstance(v, DAGNode) else v)
                  for k, v in self._kwargs.items()}
        ref = self._fn.remote(*args, **kwargs)
        cache[key] = ref
        return ref


class ClassNode(DAGNode):
    """An actor instantiation in the graph; method calls become nodes."""

    def __init__(self, actor_cls, args, kwargs):
        self._cls = actor_cls
        self._args = args
        self._kwargs = kwargs
        self._handle = None

    def _get_handle(self, inputs, cache):
        if self._handle is None:
            args = [a._execute(inputs, cache) if isinstance(a, DAGNode) else a
                    for a in self._args]
            kwargs = {k: (v._execute(inputs, cache) if isinstance(v, DAGNode) else v)
                      for k, v in self._kwargs.items()}
            self._handle = self._cls.remote(*args, **kwargs)
        return self._handle

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return _ClassMethodBinder(self, name)

    def _execute(self, inputs, cache):
        return self._get_handle(inputs, cache)


class _ClassMethodBinder:
    def __init__(self, class_node: ClassNode, method: str):
        self._node = class_node
        self._method = method

    def bind(self, *args, **kwargs) -> "ClassMethodNode":
        return ClassMethodNode(self._node, self._method, args, kwargs)


class ClassMethodNode(DAGNode):
    def __init__(self, class_node: ClassNode, method: str, args, kwargs):
        self._class_node = class_node
        self._method = method
        self._args = args
        self._kwargs = kwargs

    def _execute(self, inputs, cache):
        key = id(self)
        if key in cache:
            return cache[key]
        handle = self._class_node._get_handle(inputs, cache)
        args = [a._execute(inputs, cache) if isinstance(a, DAGNode) else a
                for a in self._args]
        kwargs = {k: (v._execute(inputs, cache) if isinstance(v, DAGNode) else v)
                  for k, v in self._kwargs.items()}
        ref = getattr(handle, self._method).remote(*args, **kwargs)
        cache[key] = ref
        return ref


def _bind_function(remote_fn, *args, **kwargs) -> FunctionNode:
    return FunctionNode(remote_fn, args, kwargs)


def _bind_class(actor_cls, *args, **kwargs) -> ClassNode:
    return ClassNode(actor_cls, args, kwargs)


def install_bind() -> None:
    """Add `.bind()` to RemoteFunction and ActorClass (done at import)."""
    from ray_tpu.core.actor import ActorClass
    from ray_tpu.core.api import RemoteFunction

    if not hasattr(RemoteFunction, "bind"):
        RemoteFunction.bind = lambda self, *a, **k: _bind_function(self, *a, **k)
    if not hasattr(ActorClass, "bind"):
        ActorClass.bind = lambda self, *a, **k: _bind_class(self, *a, **k)


install_bind()
